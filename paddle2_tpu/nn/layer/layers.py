"""nn.Layer base class (python/paddle/nn/layer/layers.py:354 parity).

A mutable module tree holding Parameters (Tensors with stop_gradient=False)
and buffers. Eager forward runs ops on the tape; under jit.to_static the same
forward is traced functionally with parameters swapped for traced values
(jit/functional.py), which is the TPU-fast path.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import core
from ...framework.tensor import Parameter, Tensor
from .. import initializer as I


class ParamAttr:
    """paddle.ParamAttr parity: per-parameter config."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        return ParamAttr()


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks, self._id = hooks, hook_id

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self._dtype = core.convert_dtype(dtype)
        self.training = True
        self._full_name = name_scope or self.__class__.__name__.lower()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0

    # -- parameter/buffer management ------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Optional[Parameter]:
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = core.convert_dtype(dtype) or self._dtype
        init = attr.initializer or default_initializer
        if init is None:
            gw, gb = I.get_global_initializer()
            if is_bias:
                init = gb or I.Constant(0.0)
            else:
                init = gw or I.XavierNormal()
        data = init(shape, dtype)
        p = Parameter(data, name=attr.name or "", trainable=attr.trainable)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.need_clip = attr.need_clip
        p.regularizer = attr.regularizer
        return p

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute magic -------------------------------------------------
    def __setattr__(self, name: str, value: Any):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            params[name] = value
            self.__dict__.pop(name, None)  # a plain attr would shadow us
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning layers")
            layers[name] = value
            self.__dict__.pop(name, None)
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
        elif buffers is not None and name in buffers:
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = (list(self._parameters) + list(self._sub_layers)
                 + list(self._buffers))
        return super().__dir__() + extra

    # -- traversal -------------------------------------------------------
    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False,
                        layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, l in self.named_children():
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True,
                                         layers_set=layers_set)

    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        layers = (self.named_sublayers(prefix=prefix, include_self=True)
                  if include_sublayers else [(prefix, self)])
        for lpfx, layer in layers:
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (lpfx + ("." if lpfx else "") + pname, p)

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        layers = (self.named_sublayers(prefix=prefix, include_self=True)
                  if include_sublayers else [(prefix, self)])
        for lpfx, layer in layers:
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (lpfx + ("." if lpfx else "") + bname, b)

    # -- mode ------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- hooks -----------------------------------------------------------
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- execution -------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    # -- state dict ------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True
                   ) -> Dict[str, Tensor]:
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip("."),
                                             include_sublayers=include_sublayers):
            dest[name] = p
        layers = (self.named_sublayers(include_self=True)
                  if include_sublayers else [("", self)])
        for lpfx, layer in layers:
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                key = lpfx + ("." if lpfx else "") + bname
                if structured_name_prefix.rstrip("."):
                    key = structured_name_prefix.rstrip(".") + "." + key
                dest[key] = b
        return dest

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for key, target in own.items():
            if key in state_dict:
                value = state_dict[key]
                arr = (value.numpy() if isinstance(value, Tensor)
                       else np.asarray(value))
                target.set_value(arr)
            else:
                missing.append(key)
        for key in state_dict:
            if key not in own:
                unexpected.append(key)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- dtype/device ----------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = core.convert_dtype(dtype)
            self._dtype = dt
            for p in self.parameters():
                if jnp.issubdtype(p._data.dtype, jnp.floating):
                    p._replace_data(p._data.astype(dt))
            for b in self.buffers():
                if b is not None and jnp.issubdtype(b._data.dtype, jnp.floating):
                    b._replace_data(b._data.astype(dt))
        if device is not None:
            name, _, idx = str(device).partition(":")
            place = (core.CPUPlace(int(idx or 0)) if name == "cpu"
                     else core.TPUPlace(int(idx or 0)))
            dev = place.jax_device()
            for t in list(self.parameters()) + [b for b in self.buffers()
                                                if b is not None]:
                t._replace_data(jax.device_put(t._data, dev))
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def full_name(self):
        return self._full_name

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{self.__class__.__name__}({extra}" if extra
                 else f"{self.__class__.__name__}("]
        for name, sub in self.named_children():
            sub_repr = repr(sub).split("\n")
            sub_repr = [sub_repr[0]] + ["  " + ln for ln in sub_repr[1:]]
            lines.append(f"  ({name}): " + "\n".join(sub_repr))
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else lines[0] + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()
