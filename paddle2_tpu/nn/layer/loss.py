"""Loss layers (python/paddle/nn/layer/loss.py parity)."""

from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["SoftMarginLoss", "MultiLabelSoftMarginLoss",
           "MultiMarginLoss", "GaussianNLLLoss",
           "TripletMarginWithDistanceLoss", "CrossEntropyLoss", "NLLLoss", "BCELoss", "BCEWithLogitsLoss",
           "MSELoss", "L1Loss", "SmoothL1Loss", "KLDivLoss",
           "MarginRankingLoss", "HingeEmbeddingLoss", "CosineEmbeddingLoss",
           "CTCLoss", "TripletMarginLoss", "PoissonNLLLoss", "HuberLoss",
           "HSigmoidLoss", "AdaptiveLogSoftmaxWithLoss", "RNNTLoss"]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.args = dict(ignore_index=ignore_index, reduction=reduction,
                         soft_label=soft_label, axis=axis,
                         use_softmax=use_softmax,
                         label_smoothing=label_smoothing)

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight, **self.args)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight, self.ignore_index, self.reduction = (weight, ignore_index,
                                                          reduction)

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight, self.reduction, self.pos_weight = (weight, reduction,
                                                        pos_weight)

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction, self.delta = reduction, delta

    def forward(self, input, label):
        return F.huber_loss(input, label, self.delta, self.reduction)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction, self.log_target = reduction, log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, *self.args)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, *self.args)



class SoftMarginLoss(Layer):
    """nn.SoftMarginLoss (layer/loss.py parity)."""

    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.full, self.epsilon = full, epsilon
        self.reduction = reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap = margin, swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class HSigmoidLoss(Layer):
    """layer/loss.py HSigmoidLoss: learnable hierarchical-softmax tree
    over ``num_classes`` leaves (weight rows = internal nodes)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if not is_custom and num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self.num_classes = num_classes
        self.is_custom = is_custom
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_classes - 1, 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table=path_table,
                               path_code=path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """layer/loss.py:2409 AdaptiveLogSoftmaxWithLoss: head shortlist +
    div_value-shrunk tail clusters."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if (cutoffs != sorted(cutoffs) or min(cutoffs) <= 0
                or max(cutoffs) > n_classes - 1
                or len(set(cutoffs)) != len(cutoffs)):
            raise ValueError(
                "cutoffs should be a sequence of unique, positive "
                "integers sorted in an increasing order, each < n_classes")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        self.shortlist_size = self.cutoffs[0]
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.shortlist_size + self.n_clusters
        self.head_weight = self.create_parameter(
            [in_features, self.head_size], attr=weight_attr)
        self.head_bias = self.create_parameter(
            [self.head_size], attr=bias_attr,
            is_bias=True) if head_bias else None
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = self.create_parameter([in_features, hsz],
                                         attr=weight_attr)
            out = self.create_parameter([hsz, osz], attr=weight_attr)
            self.add_parameter(f"tail_proj_{i}", proj)
            self.add_parameter(f"tail_out_{i}", out)
            self.tail_weights.append([proj, out])

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs[:-1], self.head_bias)

    def log_prob(self, input):
        """Full [N, n_classes] log-probabilities (layer/loss.py
        AdaptiveLogSoftmaxWithLoss.log_prob)."""
        import jax
        import jax.numpy as jnp
        from ...ops.dispatch import apply_op, ensure_tensor
        tensors = [ensure_tensor(input), ensure_tensor(self.head_weight)]
        if self.head_bias is not None:
            tensors.append(ensure_tensor(self.head_bias))
        for proj, out in self.tail_weights:
            tensors.append(ensure_tensor(proj))
            tensors.append(ensure_tensor(out))
        short, k = self.shortlist_size, self.n_clusters
        cuts = self.cutoffs

        def fn(x, hw, *rest):
            i = 0
            hb = None
            if self.head_bias is not None:
                hb = rest[0]
                i = 1
            head = x @ hw
            if hb is not None:
                head = head + hb
            head_lp = jax.nn.log_softmax(head, axis=-1)
            pieces = [head_lp[:, :short]]
            for c in range(k):
                proj, ow = rest[i + 2 * c], rest[i + 2 * c + 1]
                tail_lp = jax.nn.log_softmax((x @ proj) @ ow, axis=-1)
                pieces.append(head_lp[:, short + c:short + c + 1]
                              + tail_lp)
            return jnp.concatenate(pieces, axis=1)

        return apply_op("adaptive_log_prob", fn, tuple(tensors), {})

    def predict(self, input):
        lp = self.log_prob(input)
        from ...ops import math as _m
        return lp.argmax(axis=-1)


class RNNTLoss(Layer):
    """layer/loss.py RNNTLoss wrapper over F.rnnt_loss."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)
