"""Norm layers (python/paddle/nn/layer/norm.py parity)."""

from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from .layers import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "RMSNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "GroupNorm", "LocalResponseNorm",
           "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True,
                                          default_initializer=I.Constant(0.0))
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features)))

    def forward(self, input):
        return F.batch_norm(
            input, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCW" if data_format == "NCL" else "NWC",
                         use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. Inside pjit/shard_map the mean/var reductions
    become XLA all-reduces over the dp axis automatically when the batch is
    sharded (GSPMD); as a dygraph layer on one chip it equals BatchNorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            out.weight.set_value(layer.weight)
            out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = (self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
            if weight_attr is not False else None)
        self.bias = (self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))
            if bias_attr is not False else None)

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            list(normalized_shape), attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, input):
        return F.rms_norm(input, self.weight, self._epsilon)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True,
                default_initializer=I.Constant(0.0))
        else:
            self.weight = None
            self.bias = None

    def forward(self, input):
        return F.instance_norm(input, weight=self.weight, bias=self.bias,
                               eps=self._epsilon,
                               data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, "NCW")


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = (self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
            if weight_attr is not False else None)
        self.bias = (self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))
            if bias_attr is not False else None)

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon,
                            self.weight, self.bias, self._data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, input):
        return F.local_response_norm(input, *self.args)


class SpectralNorm(Layer):
    """Power-iteration spectral normalization of a weight tensor."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...ops.dispatch import apply_op, ensure_tensor
        weight = ensure_tensor(weight)
        dim, eps, iters = self._dim, self._epsilon, self._power_iters
        u0, v0 = self.weight_u._data, self.weight_v._data

        def fn(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma
        return apply_op("spectral_norm", fn, (weight,), {})
