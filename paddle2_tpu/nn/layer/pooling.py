"""Pooling layers (python/paddle/nn/layer/pooling.py parity)."""

from __future__ import annotations

from .. import functional as F
from .layers import Layer

__all__ = ["AvgPool1D", "AvgPool2D", "AvgPool3D", "MaxPool1D", "MaxPool2D",
           "MaxPool3D", "AdaptiveAvgPool1D", "AdaptiveAvgPool2D",
           "AdaptiveAvgPool3D", "AdaptiveMaxPool1D", "AdaptiveMaxPool2D",
           "AdaptiveMaxPool3D", "LPPool1D", "LPPool2D", "MaxUnPool1D",
           "MaxUnPool2D", "MaxUnPool3D", "FractionalMaxPool2D",
           "FractionalMaxPool3D"]


class _Pool(Layer):
    fn = None

    def __init__(self, kernel_size, stride=None, padding=0, **kwargs):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.kwargs = kwargs

    def forward(self, x):
        return type(self).fn(x, self.kernel_size, self.stride, self.padding,
                             **self.kwargs)


class AvgPool1D(_Pool):
    fn = staticmethod(F.avg_pool1d)


class AvgPool2D(_Pool):
    fn = staticmethod(F.avg_pool2d)


class AvgPool3D(_Pool):
    fn = staticmethod(F.avg_pool3d)


class MaxPool1D(_Pool):
    fn = staticmethod(F.max_pool1d)


class MaxPool2D(_Pool):
    fn = staticmethod(F.max_pool2d)


class MaxPool3D(_Pool):
    fn = staticmethod(F.max_pool3d)


class _AdaptivePool(Layer):
    fn = None

    def __init__(self, output_size, **kwargs):
        super().__init__()
        self.output_size = output_size
        self.kwargs = kwargs

    def forward(self, x):
        return type(self).fn(x, self.output_size, **self.kwargs)


class AdaptiveAvgPool1D(_AdaptivePool):
    fn = staticmethod(F.adaptive_avg_pool1d)


class AdaptiveAvgPool2D(_AdaptivePool):
    fn = staticmethod(F.adaptive_avg_pool2d)


class AdaptiveAvgPool3D(_AdaptivePool):
    fn = staticmethod(F.adaptive_avg_pool3d)


class AdaptiveMaxPool1D(_AdaptivePool):
    fn = staticmethod(F.adaptive_max_pool1d)


class AdaptiveMaxPool2D(_AdaptivePool):
    fn = staticmethod(F.adaptive_max_pool2d)


class AdaptiveMaxPool3D(_AdaptivePool):
    fn = staticmethod(F.adaptive_max_pool3d)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self._args = (norm_type, kernel_size, stride, padding, ceil_mode,
                      data_format)

    def forward(self, x):
        return F.lp_pool1d(x, *self._args)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self._args = (norm_type, kernel_size, stride, padding, ceil_mode,
                      data_format)

    def forward(self, x):
        return F.lp_pool2d(x, *self._args)


class _MaxUnPool(Layer):
    fn = None

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format=None, output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        return type(self).fn(x, indices, self.kernel_size, self.stride,
                             self.padding, output_size=self.output_size)


class MaxUnPool1D(_MaxUnPool):
    fn = staticmethod(F.max_unpool1d)


class MaxUnPool2D(_MaxUnPool):
    fn = staticmethod(F.max_unpool2d)


class MaxUnPool3D(_MaxUnPool):
    fn = staticmethod(F.max_unpool3d)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        return F.fractional_max_pool2d(x, *self._args)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        return F.fractional_max_pool3d(x, *self._args)
