"""Recurrent layers (python/paddle/nn/layer/rnn.py parity).

Each cell exposes a pure `step(x_t, states, *params)` function; RNN records
ONE tape op whose forward is a lax.scan over time — the XLA-native recurrence
(static trip count, one compiled kernel, O(1) tape nodes instead of O(T)).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op, ensure_tensor
from .. import initializer as I
from .layers import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "GRUCell", "LSTMCell", "RNN", "SimpleRNN", "GRU",
           "LSTM", "BiRNN"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ...ops.creation import full
        batch = batch_ref.shape[batch_dim_idx]
        if isinstance(self.state_shape[0], (list, tuple)):
            return tuple(full([batch] + list(s), init_value,
                              dtype or "float32") for s in self.state_shape)
        return full([batch] + list(self.state_shape), init_value,
                    dtype or "float32")

    def _params(self):
        return (self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)

    # pure step: (x_t, state_tuple, *param_arrays) -> (out, new_state_tuple)
    @staticmethod
    def step(x, states, wi, wh, bi, bh):
        raise NotImplementedError

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        single = not isinstance(states, (tuple, list))
        state_list = [states] if single else list(states)
        inputs = ensure_tensor(inputs)
        n_states = len(state_list)
        cls = type(self)
        extra = ({"activation": self.activation}
                 if isinstance(self, SimpleRNNCell) else {})

        def fn(x, *rest):
            st = tuple(rest[:n_states])
            params = rest[n_states:]
            out, new_st = cls.step(x, st if not single else (st[0],), *params,
                                   **extra)
            return (out,) + tuple(new_st if isinstance(new_st, tuple)
                                  else (new_st,))
        outs = apply_op(cls.__name__, fn,
                        (inputs, *[ensure_tensor(s) for s in state_list],
                         *self._params()), {})
        out = outs[0]
        new_states = outs[1] if single and len(outs) == 2 else tuple(outs[1:])
        return out, new_states


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    @staticmethod
    def step(x, states, wi, wh, bi, bh, activation="tanh"):
        (h,) = states
        act = jnp.tanh if activation == "tanh" else jax.nn.relu
        out = act(x @ wi.T + bi + h @ wh.T + bh)
        return out, (out,)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    @staticmethod
    def step(x, states, wi, wh, bi, bh):
        (h,) = states
        hs = wh.shape[1]
        gi = x @ wi.T + bi
        gh = h @ wh.T + bh
        r = jax.nn.sigmoid(gi[..., :hs] + gh[..., :hs])
        z = jax.nn.sigmoid(gi[..., hs:2 * hs] + gh[..., hs:2 * hs])
        c = jnp.tanh(gi[..., 2 * hs:] + r * gh[..., 2 * hs:])
        out = (1 - z) * c + z * h
        return out, (out,)


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=u)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True, default_initializer=u)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True, default_initializer=u)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    @staticmethod
    def step(x, states, wi, wh, bi, bh):
        hp, cp = states
        hs = wh.shape[1]
        gates = x @ wi.T + bi + hp @ wh.T + bh
        i = jax.nn.sigmoid(gates[..., :hs])
        f = jax.nn.sigmoid(gates[..., hs:2 * hs])
        g = jnp.tanh(gates[..., 2 * hs:3 * hs])
        o = jax.nn.sigmoid(gates[..., 3 * hs:])
        cn = f * cp + i * g
        hn = o * jnp.tanh(cn)
        return hn, (hn, cn)


class RNN(Layer):
    """Sequence scan around a cell: one lax.scan per forward
    (python/paddle/nn/layer/rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        inputs = ensure_tensor(inputs)
        batch_idx = 1 if self.time_major else 0
        if initial_states is None:
            initial_states = self.cell.get_initial_states(
                inputs, batch_dim_idx=batch_idx)
        single = not isinstance(initial_states, (tuple, list))
        state_list = [initial_states] if single else list(initial_states)
        n_states = len(state_list)
        time_axis = 0 if self.time_major else 1
        step = type(self.cell).step
        reverse = self.is_reverse
        extra = ({"activation": self.cell.activation}
                 if isinstance(self.cell, SimpleRNNCell) else {})

        def fn(x, *rest):
            st = tuple(rest[:n_states])
            params = rest[n_states:]
            xs = jnp.moveaxis(x, time_axis, 0)
            if reverse:
                xs = jnp.flip(xs, axis=0)

            def body(carry, x_t):
                out, new_st = step(x_t, carry, *params, **extra)
                return tuple(new_st), out

            final_st, outs = jax.lax.scan(body, st, xs)
            if reverse:
                outs = jnp.flip(outs, axis=0)
            outs = jnp.moveaxis(outs, 0, time_axis)
            return (outs,) + tuple(final_st)

        results = apply_op("rnn_scan", fn,
                           (inputs, *[ensure_tensor(s) for s in state_list],
                            *self.cell._params()), {})
        outputs = results[0]
        final = results[1] if single and len(results) == 2 else tuple(results[1:])
        return outputs, final


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states_fw, states_bw = (initial_states if initial_states is not None
                                else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, states_fw)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw)
        from ...ops.manipulation import concat
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    cell_cls = None
    n_states = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, **cell_kwargs):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirectional else 1
        self.num_directions = num_dirs
        from .container import LayerList
        layers = []
        for i in range(num_layers):
            in_sz = input_size if i == 0 else hidden_size * num_dirs
            if self.bidirectional:
                layers.append(BiRNN(
                    self._make_cell(in_sz, hidden_size, weight_ih_attr,
                                    weight_hh_attr, bias_ih_attr, bias_hh_attr,
                                    **cell_kwargs),
                    self._make_cell(in_sz, hidden_size, weight_ih_attr,
                                    weight_hh_attr, bias_ih_attr, bias_hh_attr,
                                    **cell_kwargs),
                    time_major))
            else:
                layers.append(RNN(
                    self._make_cell(in_sz, hidden_size, weight_ih_attr,
                                    weight_hh_attr, bias_ih_attr, bias_hh_attr,
                                    **cell_kwargs),
                    False, time_major))
        self.rnns = LayerList(layers)

    def _make_cell(self, in_sz, hid, wi, wh, bi, bh, **kw):
        return type(self).cell_cls(in_sz, hid, weight_ih_attr=wi,
                                   weight_hh_attr=wh, bias_ih_attr=bi,
                                   bias_hh_attr=bh, **kw)

    def _split_initial(self, initial_states):
        """paddle packs initial states as (num_layers*num_dirs, batch, hidden)
        tensors (h for GRU/SimpleRNN; (h, c) tuple for LSTM). Split per
        layer/direction."""
        if initial_states is None:
            return [None] * self.num_layers
        from ...ops.manipulation import unstack
        if isinstance(initial_states, (tuple, list)):
            hs = unstack(initial_states[0], axis=0)
            cs = unstack(initial_states[1], axis=0)
            packed = [(hs[i], cs[i]) for i in range(len(hs))]
        else:
            packed = [(h,) for h in unstack(initial_states, axis=0)]
        per_layer = []
        nd = self.num_directions
        for i in range(self.num_layers):
            if nd == 2:
                fw = packed[2 * i]
                bw = packed[2 * i + 1]
                fw = fw if len(fw) > 1 else fw[0]
                bw = bw if len(bw) > 1 else bw[0]
                per_layer.append((fw, bw))
            else:
                st = packed[i]
                per_layer.append(st if len(st) > 1 else st[0])
        return per_layer

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from .. import functional as F
        out = inputs
        per_layer_states = self._split_initial(initial_states)
        final_states = []
        for i, rnn in enumerate(self.rnns):
            out, st = rnn(out, per_layer_states[i])
            final_states.append(st)
            if self.dropout and i < self.num_layers - 1:
                out = F.dropout(out, self.dropout, training=self.training)
        return out, final_states


class SimpleRNN(_RNNBase):
    cell_cls = SimpleRNNCell

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation, **kwargs)


class GRU(_RNNBase):
    cell_cls = GRUCell


class LSTM(_RNNBase):
    cell_cls = LSTMCell
    n_states = 2
