"""paddle.nn.utils (reference python/paddle/nn/utils/): weight
parametrizations + parameter/vector helpers + grad clipping."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..utils_mod import clip_grad_norm_, clip_grad_value_  # noqa: F401
from ...framework.tensor import Tensor

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_"]


def _norm_except(w, dim):
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """weight_norm_hook.py: reparameterize `name` as g * v/||v||,
    recomputed before every forward via a pre-hook."""
    w = getattr(layer, name)
    dim = (w.ndim - 1) if dim is None else int(dim)
    g0 = _norm_except(w._data, dim)
    from ..layer.layers import Layer
    v = layer.create_parameter(list(w.shape))
    v._replace_data(w._data)
    g = layer.create_parameter(list(g0.shape))
    g._replace_data(g0)
    layer.add_parameter(f"{name}_v", v)
    layer.add_parameter(f"{name}_g", g)
    # the original param stops being trainable; forward recomputes it
    w.stop_gradient = True

    def _recompute(layer_, inputs):
        from ...ops.dispatch import apply_op
        out = apply_op(
            "weight_norm",
            lambda vv, gg: gg * vv / jnp.maximum(
                _norm_except(vv, dim), 1e-12), (v, g), {})
        getattr(layer_, name)._replace_data(out._data)
        # keep the tape connection: assign the COMPUTED tensor so grads
        # flow to v and g
        object.__setattr__(layer_, name, out)
        return None

    handle = layer.register_forward_pre_hook(_recompute)
    layer._weight_norm_state = (name, v, g, handle, w, dim)
    return layer


def remove_weight_norm(layer, name="weight"):
    state = getattr(layer, "_weight_norm_state", None)
    if state is None:
        return layer
    name_, v, g, handle, orig, dim = state
    handle.remove()
    w = g._data * v._data / jnp.maximum(_norm_except(v._data, dim),
                                        1e-12)
    orig._replace_data(w)
    orig.stop_gradient = False
    object.__setattr__(layer, name_, orig)
    # drop the now-dead reparameterization params so parameters()/
    # state_dict round-trip like an unwrapped layer
    layer._parameters.pop(f"{name_}_v", None)
    layer._parameters.pop(f"{name_}_g", None)
    del layer._weight_norm_state
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """spectral_norm_hook.py: divide the weight by its largest singular
    value, estimated by power iteration before each forward. The
    TRAINABLE parameter is ``<name>_orig`` (reference weight_orig); the
    consumed weight is recomputed from it each forward so the optimizer
    keeps training through the normalization."""
    w = getattr(layer, name)
    dim = 0 if dim is None else int(dim)
    mat = jnp.moveaxis(w._data, dim, 0).reshape(w.shape[dim], -1)
    import numpy.random as npr
    u0 = jnp.asarray(npr.RandomState(0).randn(mat.shape[0]), jnp.float32)
    v0 = jnp.asarray(npr.RandomState(1).randn(mat.shape[1]), jnp.float32)
    state = {"u": u0 / jnp.linalg.norm(u0),
             "v": v0 / jnp.linalg.norm(v0)}
    orig = layer.create_parameter(list(w.shape))
    orig._replace_data(w._data)
    layer.add_parameter(f"{name}_orig", orig)
    w.stop_gradient = True

    def _apply(layer_, inputs):
        from ...ops.dispatch import apply_op
        # sigma from the LIVE weight_orig (updated by the optimizer);
        # the power-iteration vectors carry across steps
        wd = orig._data
        m = jnp.moveaxis(wd, dim, 0).reshape(wd.shape[dim], -1)
        u, vvec = state["u"], state["v"]
        for _ in range(n_power_iterations):
            vvec = m.T @ u
            vvec = vvec / jnp.maximum(jnp.linalg.norm(vvec), eps)
            u = m @ vvec
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        state["u"], state["v"] = (jax.lax.stop_gradient(u),
                                  jax.lax.stop_gradient(vvec))
        u_c, v_c = state["u"], state["v"]

        def norm_fn(wo):
            mm = jnp.moveaxis(wo, dim, 0).reshape(wo.shape[dim], -1)
            sigma = u_c @ (mm @ v_c)
            return wo / sigma

        out = apply_op("spectral_norm", norm_fn, (orig,), {})
        getattr(layer_, name)._replace_data(out._data)
        object.__setattr__(layer_, name, out)
        return None

    handle = layer.register_forward_pre_hook(_apply)
    layer._spectral_norm_state = (name, handle, orig)
    return layer


def parameters_to_vector(parameters, name=None):
    """utils/transform_parameters.py: flatten params into one vector."""
    arrs = [p._data.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(arrs))


def vector_to_parameters(vec, parameters, name=None):
    v = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if len(p.shape) else 1
        p._replace_data(v[off:off + n].reshape(tuple(p.shape)))
        off += n
