"""nn.utils (python/paddle/nn/utils/ parity: clip_grad_*, params flatten)."""

import jax.numpy as jnp

from ..framework.tensor import Tensor


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    params = [p for p in (parameters if isinstance(parameters, (list, tuple))
                          else [parameters]) if p.grad is not None]
    if not params:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = max(float(jnp.max(jnp.abs(p.grad._data))) for p in params)
        total_norm = jnp.asarray(total)
    else:
        total_norm = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(p.grad._data.astype(jnp.float32)) ** norm_type)
             for p in params])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / (total_norm + 1e-6), 1.0)
    for p in params:
        p.grad._replace_data((p.grad._data.astype(jnp.float32) * scale)
                             .astype(p.grad._data.dtype))
    return Tensor(total_norm)


def clip_grad_value_(parameters, clip_value):
    params = parameters if isinstance(parameters, (list, tuple)) else [parameters]
    for p in params:
        if p.grad is not None:
            p.grad._replace_data(jnp.clip(p.grad._data, -clip_value, clip_value))
