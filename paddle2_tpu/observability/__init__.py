"""paddle2_tpu.observability — the performance & health observatory.

Three coordinated planes over one training process:

* :mod:`.metrics` — always-on Counter/Gauge/Histogram registry with a
  per-rank JSONL stream (``PADDLE_METRICS_DIR/metrics_rank_N.jsonl``)
  and Prometheus textfile export; step-time breakdown via step windows
  (input / compute / collective / host, summing exactly to the step
  total);
* :mod:`.cost_model` — deterministic XLA step-cost accounting (FLOPs,
  HBM bytes, collective wire traffic under an ICI-vs-DCN link model,
  MFU, roofline) — the cost x rate gating primitive the perf benches
  use instead of wall-clock A/B;
* :mod:`.tracing` — per-REQUEST lifecycle span trees for the serving
  fleet (``PADDLE_TRACE_DIR/trace_rank_N.jsonl`` + chrome-trace
  export) with an exact tail-latency decomposition — the
  ``serve_doctor`` CLI's substrate;
* ``tools/perf_doctor`` (sibling CLI of ``flight_doctor``) — joins the
  metrics stream with flight rings and merged chrome traces into a
  triage report, and diffs two streams to name the top regressed
  component.

The metrics and tracing hooks follow the flight recorder's
zero-overhead discipline: one module-attribute load per site when
disabled.
"""

from . import cost_model, metrics, tracing  # noqa: F401
from .cost_model import (CollectiveTraffic, LinkModel, StepCost,  # noqa: F401
                         chip_peak, program_cost, wire_bytes)
from .metrics import (Counter, Gauge, Histogram, MetricsPlane,  # noqa: F401
                      METRICS_DIR_ENV)
from .tracing import TracePlane, TRACE_DIR_ENV  # noqa: F401

__all__ = ["metrics", "cost_model", "tracing", "Counter", "Gauge",
           "Histogram", "MetricsPlane", "METRICS_DIR_ENV", "TracePlane",
           "TRACE_DIR_ENV", "CollectiveTraffic", "LinkModel", "StepCost",
           "chip_peak", "program_cost", "wire_bytes"]
