"""Deterministic XLA step-cost model: FLOPs, bytes, wire traffic, MFU.

Wall clocks lie in shared sandboxes (and on real pods they conflate the
thing you changed with whatever the neighbors are doing), so every perf
gate in this repo is **cost x rate**: deterministic op accounting from
the compiled program itself, times a hardware rate model. This module
is the accounting half:

* :func:`program_cost` — XLA ``cost_analysis`` of a lowered executable
  (FLOPs, bytes accessed, transcendentals). Deterministic: the same
  program lowers to the same numbers on every run.
* :func:`wire_bytes` — algorithm bytes-on-wire per rank for each
  collective kind (ring all_reduce moves ``2(n-1)/n`` of the payload,
  gather/scatter variants ``(n-1)/n``, ...), the standard bandwidth-
  optimal-algorithm accounting.
* :class:`LinkModel` — per-mesh-axis bandwidth: ICI (intra-pod torus
  links) vs DCN (cross-pod data-center network), because a collective
  over a DCN-mapped axis is an order of magnitude slower per byte and
  the sharding-defaults work on ROADMAP item 1 is exactly about keeping
  heavy collectives off that axis.
* :class:`CollectiveTraffic` — an accumulator the eager collective path
  (and hybrid-parallel planners) feed; converts to seconds under a
  :class:`LinkModel`.
* :class:`StepCost` — joins program FLOPs + HBM bytes + wire traffic
  into a roofline (compute- / memory- / network-bound verdict), MFU
  against the chip peak, and a deterministic step-time lower bound —
  the gating primitive the pod-scale scaling bench uses instead of
  wall-clock A/B.

Everything here is jax-optional at import (the ``perf_doctor`` CLI and
the analytic helpers work anywhere); only :func:`program_cost` touches
jax, lazily.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# -- hardware rate tables ------------------------------------------------
# nominal bf16 dense peak per chip (FLOP/s) and HBM bandwidth (B/s),
# keyed on device_kind substrings; env-overridable for odd deployments
CHIP_PEAKS: Dict[str, Tuple[float, float]] = {
    # kind-substring: (peak_flops, hbm_bytes_per_s)
    "v5 lite": (197e12, 819e9), "v5e": (197e12, 819e9),
    "v5litepod": (197e12, 819e9),
    "v4": (275e12, 1228e9), "v5p": (459e12, 2765e9),
    "v6 lite": (918e12, 1640e9), "v6e": (918e12, 1640e9),
    "trillium": (918e12, 1640e9),
}
# HBM capacity per chip generation (GB) — the remat searcher's budget
# denominator and the single-chip bench's declared-budget source
CHIP_HBM_GB: Dict[str, float] = {
    "v5 lite": 16.0, "v5e": 16.0, "v5litepod": 16.0,
    "v4": 32.0, "v5p": 95.0,
    "v6 lite": 32.0, "v6e": 32.0, "trillium": 32.0,
}
_DEFAULT_PEAK = (197e12, 819e9)          # v5e-assumed
_DEFAULT_HBM_GB = 16.0
# CPU fallback: a deliberately round nominal figure so MFU numbers off
# accelerators are obviously synthetic rather than silently wrong
_CPU_PEAK = (1e11, 5e10)

PEAK_ENV = "PADDLE_PEAK_TFLOPS"
HBM_ENV = "PADDLE_HBM_GBPS"
ICI_ENV = "PADDLE_ICI_GBPS"
DCN_ENV = "PADDLE_DCN_GBPS"
ICI_LATENCY_ENV = "PADDLE_ICI_LATENCY_US"
DCN_LATENCY_ENV = "PADDLE_DCN_LATENCY_US"
DCN_AXES_ENV = "PADDLE_DCN_AXES"

# defaults: v4/v5 ICI is ~100 GB/s per link per direction; DCN per host
# lands around 12.5 GB/s (100 Gbps) — both env-overridable. These are
# THE nominal wire rates every bench lane prices with: one shared pair
# of names, so efficiencies stay comparable across lanes (a literal
# duplicated inline would silently drift).
DEFAULT_ICI_GBPS = 90.0
DEFAULT_DCN_GBPS = 12.5
_DEFAULT_ICI_GBPS = DEFAULT_ICI_GBPS
_DEFAULT_DCN_GBPS = DEFAULT_DCN_GBPS
# nominal per-dispatch collective setup cost (the α of an α+β link
# model): ICI collectives launch in ~microseconds; a cross-slice DCN
# collective pays multi-hop fabric + rendezvous setup (hundreds of
# microseconds at pod scale). LinkModel defaults its latencies to ZERO
# so existing cost×rate artifacts are bitwise unchanged — a lane that
# wants latency-aware accounting opts in explicitly with these
# nominals (or via env).
DEFAULT_ICI_LATENCY_US = 1.0
DEFAULT_DCN_LATENCY_US = 250.0

# host-offload link (PCIe-class; v5e host DMA lands ~25 GB/s per dir).
# Owned here so the remat offload policy (incubate/autotune.py) and the
# serving KV spill tier price the SAME channel from one pair of names —
# a literal duplicated in each lane would silently drift. The env name
# predates this move and is kept for compatibility.
HOST_ENV = "PADDLE_OFFLOAD_GBPS"
DEFAULT_HOST_GBPS = 25.0
_DEFAULT_HOST_GBPS = DEFAULT_HOST_GBPS


def host_link_bps(override_gbps=None) -> float:
    """Host<->device offload-link rate in bytes/s (env-overridable).

    ``override_gbps`` (GB/s) wins over the ``PADDLE_OFFLOAD_GBPS`` env
    var, which wins over :data:`DEFAULT_HOST_GBPS`."""
    if override_gbps is not None:
        return float(override_gbps) * 1e9
    return float(os.environ.get(HOST_ENV, DEFAULT_HOST_GBPS)) * 1e9


def chip_peak(device=None) -> Tuple[float, float, str]:
    """(peak_flops, hbm_bytes_per_s, label) for ``device`` (default:
    jax device 0; falls back to the CPU nominal figure without jax)."""
    env_peak = os.environ.get(PEAK_ENV)
    env_hbm = os.environ.get(HBM_ENV)
    if env_peak and env_hbm:
        return (float(env_peak) * 1e12, float(env_hbm) * 1e9,
                "env-override")
    kind = ""
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        kind = getattr(device, "device_kind", "") or ""
        platform = getattr(device, "platform", "").lower()
    except Exception:
        platform = "cpu"
    low = kind.lower()
    peak, hbm, label = None, None, ""
    for key, (p, h) in CHIP_PEAKS.items():
        if key in low:
            peak, hbm, label = p, h, key
            break
    if peak is None:
        if platform in ("", "cpu"):
            (peak, hbm), label = \
                _CPU_PEAK, f"cpu-nominal({low or 'unknown'})"
        else:
            (peak, hbm), label = \
                _DEFAULT_PEAK, f"v5e-assumed({low or 'unknown'})"
    # each override applies independently (an operator may know only
    # one of the two figures for an odd deployment)
    if env_peak:
        peak, label = float(env_peak) * 1e12, label + "+peak-env"
    if env_hbm:
        hbm, label = float(env_hbm) * 1e9, label + "+hbm-env"
    return peak, hbm, label


# -- program accounting --------------------------------------------------
def cost_analysis_of(lowered) -> Dict[str, float]:
    """Normalize jax's ``lowered.cost_analysis()`` result (dict, or a
    per-device list of dicts on older jax) to one flat dict."""
    ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return {k: float(v) for k, v in (ca or {}).items()
            if isinstance(v, (int, float))}


def program_cost(entry, call_args: Sequence[Any]) -> Optional[Dict[str, float]]:
    """Deterministic op accounting of one compiled callable: lowers
    ``entry`` against ``call_args`` (concrete arrays OR
    ``jax.ShapeDtypeStruct`` avals — donation-safe) and returns XLA
    ``cost_analysis`` as ``{"flops", "bytes_accessed", ...}``. ``None``
    when the backend exposes no cost analysis."""
    try:
        lowered = entry.lower(*call_args)
        out = cost_analysis_of(lowered)
        return out or None
    except Exception:
        return None


def abstractify(call_args: Sequence[Any]) -> List[Any]:
    """Shape/dtype skeleton of ``call_args`` — safe to hold across a
    donating dispatch (the concrete buffers die with the donation) and
    accepted by ``jit(...).lower``."""
    import jax

    def _one(a):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)
        return a
    return jax.tree_util.tree_map(_one, list(call_args))


# -- collective traffic --------------------------------------------------
# bytes-on-wire factor per rank, as a multiple of the per-rank payload,
# for the bandwidth-optimal algorithm of each collective family
_WIRE_FACTORS = (
    ("all_reduce", lambda n: 2.0 * (n - 1) / n),
    ("reduce_scatter", lambda n: (n - 1) / n),
    ("all_gather", lambda n: (n - 1) / n),
    ("all_to_all", lambda n: (n - 1) / n),
    ("alltoall", lambda n: (n - 1) / n),
    ("broadcast", lambda n: (n - 1) / n),
    ("reduce", lambda n: (n - 1) / n),
    ("scatter", lambda n: (n - 1) / n),
    ("gather", lambda n: (n - 1) / n),
    ("ppermute", lambda n: 1.0),
    ("send", lambda n: 1.0),
    ("recv", lambda n: 1.0),
    ("barrier", lambda n: 0.0),
)


def wire_bytes(op: str, payload_bytes: float, group_size: int) -> float:
    """Per-rank bytes on the wire for one collective: payload x the
    algorithm factor. ``op`` matches by prefix (``all_reduce_sum`` ->
    ``all_reduce``). Unknown ops are charged the conservative full
    payload."""
    n = max(1, int(group_size))
    if n == 1:
        return 0.0
    for prefix, factor in _WIRE_FACTORS:
        if op.startswith(prefix):
            return float(payload_bytes) * factor(n)
    return float(payload_bytes)


class LinkModel:
    """Per-mesh-axis link α+β cost: latency (α, per dispatch) plus
    bandwidth (β, per byte). An axis is ICI unless named in
    ``dcn_axes`` (default: any axis whose name contains ``"dcn"``, plus
    the ``PADDLE_DCN_AXES`` comma list).

    Latencies DEFAULT TO ZERO (pure-bandwidth model — every pre-ladder
    artifact stays bitwise identical); a latency-aware lane passes
    ``ici_latency_us``/``dcn_latency_us`` explicitly or sets the
    ``PADDLE_{ICI,DCN}_LATENCY_US`` env. The α term is what makes
    bucket sizing link-class-dependent: a latency-dominated DCN hop
    wants FEWER, BIGGER buckets than ICI (see
    ``distributed.bucket.link_bucket_bytes``)."""

    def __init__(self, ici_gbps: Optional[float] = None,
                 dcn_gbps: Optional[float] = None,
                 dcn_axes: Optional[Iterable[str]] = None,
                 ici_latency_us: Optional[float] = None,
                 dcn_latency_us: Optional[float] = None):
        self.ici_bps = float(
            ici_gbps if ici_gbps is not None
            else os.environ.get(ICI_ENV, _DEFAULT_ICI_GBPS)) * 1e9
        self.dcn_bps = float(
            dcn_gbps if dcn_gbps is not None
            else os.environ.get(DCN_ENV, _DEFAULT_DCN_GBPS)) * 1e9
        self.ici_latency_s = float(
            ici_latency_us if ici_latency_us is not None
            else os.environ.get(ICI_LATENCY_ENV, 0.0)) * 1e-6
        self.dcn_latency_s = float(
            dcn_latency_us if dcn_latency_us is not None
            else os.environ.get(DCN_LATENCY_ENV, 0.0)) * 1e-6
        env_axes = os.environ.get(DCN_AXES_ENV, "")
        self.dcn_axes = set(a.strip() for a in env_axes.split(",")
                            if a.strip())
        if dcn_axes is not None:
            self.dcn_axes |= set(dcn_axes)

    def is_dcn(self, axis: Optional[str]) -> bool:
        if axis is None:
            return False
        return axis in self.dcn_axes or "dcn" in str(axis).lower()

    def link_class(self, axes: Sequence[str] = ()) -> str:
        """``"dcn"`` when the collective crosses ANY DCN-mapped axis
        (the slow hop gates the whole group), else ``"ici"``."""
        return "dcn" if any(self.is_dcn(a) for a in axes) else "ici"

    def bandwidth(self, axis: Optional[str]) -> float:
        return self.dcn_bps if self.is_dcn(axis) else self.ici_bps

    def latency(self, axes: Sequence[str] = ()) -> float:
        """Per-dispatch setup cost (α) of one collective over ``axes``:
        the slowest link class it crosses."""
        return (self.dcn_latency_s if self.link_class(axes) == "dcn"
                else self.ici_latency_s)

    def seconds(self, bytes_on_wire: float,
                axes: Sequence[str] = ()) -> float:
        """α+β time of ONE collective dispatch: setup latency plus
        transfer under the SLOWEST link it crosses (a multi-axis group
        is gated by its weakest hop). With the default zero latencies
        this is the pure-bandwidth figure it always was; multi-dispatch
        cost is modeled as one :class:`CollectiveTraffic` entry per
        dispatch."""
        if bytes_on_wire <= 0:
            return 0.0
        bw = min((self.bandwidth(a) for a in axes),
                 default=self.ici_bps)
        return float(bytes_on_wire) / bw + self.latency(axes)


def sparse_transfer_seconds(wire_bytes: float, link_class: str = "dcn",
                            link: Optional["LinkModel"] = None,
                            dispatches: int = 1,
                            host_gbps: Optional[float] = None) -> float:
    """α+β time of point-to-point sparse traffic (PS pull/push/delta)
    under one named link class, priced from the SAME LinkModel the
    collectives use so a sparse byte and a dense byte never drift.

    - ``"host"``: a worker talking to its co-located server — the
      PCIe-class :func:`host_link_bps` channel, no dispatch α (no
      fabric rendezvous on-host).
    - ``"dcn"`` / ``"ici"``: remote server — LinkModel bandwidth plus
      its per-dispatch latency, ``dispatches`` times (a pull fanning
      out to k remote shards pays k setups, not one).
    """
    if wire_bytes <= 0 and link_class == "host":
        return 0.0
    if link_class == "host":
        return float(wire_bytes) / host_link_bps(host_gbps)
    link = link or LinkModel()
    if link_class == "dcn":
        bw, alpha = link.dcn_bps, link.dcn_latency_s
    elif link_class == "ici":
        bw, alpha = link.ici_bps, link.ici_latency_s
    else:
        raise ValueError(f"unknown link class {link_class!r} "
                         "(expected host/ici/dcn)")
    return float(wire_bytes) / bw + alpha * max(1, int(dispatches))


class CollectiveTraffic:
    """Accumulator of per-step collective dispatches -> wire bytes and
    a deterministic transfer-time estimate.

    Each entry carries an ``overlappable`` mark: whether the program's
    schedule leaves independent compute for this collective to hide
    under (a bucketed grad reduce issued while backward still produces
    later buckets, a ZeRO-3 prefetch gather issued a layer ahead). The
    overlap split below is what turns "bytes on wire" into "EXPOSED
    wire time" — the only part of communication that actually extends
    the step."""

    def __init__(self):
        self.entries: List[Dict[str, Any]] = []

    def add(self, op: str, payload_bytes: float,
            axes: Sequence[str] = (), group_size: int = 1,
            overlappable: bool = False) -> None:
        self.entries.append({
            "op": op, "payload_bytes": float(payload_bytes),
            "axes": tuple(axes), "group_size": int(group_size),
            "overlappable": bool(overlappable),
            "wire_bytes": wire_bytes(op, payload_bytes, group_size)})

    def add_hierarchical_all_reduce(self, payload_bytes: float,
                                    ici_axes: Sequence[str],
                                    dcn_axes: Sequence[str],
                                    ici_group: int, dcn_group: int,
                                    overlappable: bool = False) -> None:
        """Price one HIERARCHICAL all-reduce (the ladder's grad sync):
        in-slice reduce-scatter over the ICI axes, cross-slice
        all-reduce of the 1/ici_group partial shard over DCN, in-slice
        all-gather — the ``collective.hierarchical_psum`` schedule.
        Against a flat all-reduce over the combined group this trades
        ``2(n-1)/n × payload`` at DCN bandwidth for mostly-ICI traffic
        plus a DCN hop carrying only ``payload / ici_group``."""
        payload = float(payload_bytes)
        ici_n, dcn_n = max(1, int(ici_group)), max(1, int(dcn_group))
        self.add("reduce_scatter", payload, axes=ici_axes,
                 group_size=ici_n, overlappable=overlappable)
        self.add("all_reduce_sum", payload / ici_n, axes=dcn_axes,
                 group_size=dcn_n, overlappable=overlappable)
        self.add("all_gather", payload, axes=ici_axes,
                 group_size=ici_n, overlappable=overlappable)

    def add_all_to_all_matrix(self, pair_bytes: Sequence[Sequence[float]],
                              ranks_per_slice: int,
                              ici_axes: Sequence[str] = ("ici",),
                              dcn_axes: Sequence[str] = ("dcn",),
                              hierarchical: bool = False,
                              op: str = "moe_a2a",
                              overlappable: bool = False
                              ) -> Dict[str, int]:
        """Price one token-routing all-to-all from an EXACT per-pair
        byte matrix (``pair_bytes[src][dst]``, diagonal ignored) — the
        MoE dispatch/combine case, where the payload each rank owes each
        expert host is known from the step's routing decisions rather
        than assumed uniform. Ranks are grouped into ICI slices of
        ``ranks_per_slice`` consecutive ranks; a pair within a slice
        rides ICI, a cross-slice pair rides DCN.

        - **flat**: one point-to-point dispatch per nonzero pair — every
          cross-slice pair pays its own DCN α. At small per-expert
          payloads (a few KB of routed tokens) the α term dominates:
          this is the configuration the lane requires to FAIL.
        - **hierarchical**: cross-slice payloads are bucketed per
          (src slice, dst slice) — each contributing rank forwards its
          chunk to the slice egress over ICI, ONE DCN dispatch carries
          the whole bucket, and the destination slice scatters it over
          ICI. Same bytes on the DCN, slice-pair-many α's instead of
          rank-pair-many (the ``add_hierarchical_all_reduce`` trade,
          applied to a2a).

        Returns the dispatch counts per link class (``{"ici": n,
        "dcn": n}``) so a lane can gate α-dominance explicitly. Entries
        use ``group_size=2`` so the point-to-point payload is charged in
        full (``group_size=1`` means "no wire" to :func:`wire_bytes`).
        """
        n = len(pair_bytes)
        rps = max(1, int(ranks_per_slice))
        counts = {"ici": 0, "dcn": 0}

        def _p2p(suffix: str, b: float, axes: Sequence[str],
                 cls: str) -> None:
            self.add(f"{op}_{suffix}", b, axes=axes, group_size=2,
                     overlappable=overlappable)
            counts[cls] += 1

        buckets: Dict[Tuple[int, int], float] = {}
        for i in range(n):
            for j in range(n):
                if i == j:
                    continue
                b = float(pair_bytes[i][j])
                if b <= 0:
                    continue
                si, sj = i // rps, j // rps
                if si == sj:
                    _p2p("p2p", b, ici_axes, "ici")
                elif not hierarchical:
                    _p2p("p2p", b, dcn_axes, "dcn")
                else:
                    # slice-local gather hop to the egress rank, then
                    # the mirrored scatter hop at the destination; the
                    # DCN bucket itself is added once per slice pair
                    _p2p("gather_ici", b, ici_axes, "ici")
                    _p2p("scatter_ici", b, ici_axes, "ici")
                    buckets[(si, sj)] = buckets.get((si, sj), 0.0) + b
        for (_si, _sj), b in sorted(buckets.items()):
            _p2p("bucket", b, dcn_axes, "dcn")
        return counts

    def add_ring_hops(self, block_bytes: float,
                      member_slices: Sequence[int],
                      rotations: Optional[int] = None,
                      ici_axes: Sequence[str] = ("ici",),
                      dcn_axes: Sequence[str] = ("dcn",),
                      op: str = "sep_ring",
                      overlappable: bool = False) -> Dict[str, int]:
        """Price a ring-attention K/V rotation schedule (ISSUE 20): on
        every rotation step each ring member forwards its currently-held
        K/V block to its successor, so one rotation is ``len(members)``
        point-to-point hops of ``block_bytes`` each, and a full pass is
        ``rotations`` (default ``n - 1``) such steps. ``member_slices``
        gives the ICI-slice id of each member IN RING ORDER — the ring
        ORDER is the scheduling lever this method exposes: a
        slice-contiguous order pays one DCN α per slice boundary per
        rotation, while an interleaved ("flat") order pays one per hop.
        Entries use ``group_size=2`` (point-to-point, full payload on
        the wire). Returns dispatch counts per link class, mirroring
        :meth:`add_all_to_all_matrix`, so a lane can gate α-dominance
        of the two orders both ways.
        """
        members = list(member_slices)
        n = len(members)
        if n < 2:
            return {"ici": 0, "dcn": 0}
        rot = (n - 1) if rotations is None else max(0, int(rotations))
        counts = {"ici": 0, "dcn": 0}
        for _ in range(rot):
            for m in range(n):
                same = members[m] == members[(m + 1) % n]
                if same:
                    self.add(f"{op}_hop_ici", block_bytes, axes=ici_axes,
                             group_size=2, overlappable=overlappable)
                    counts["ici"] += 1
                else:
                    self.add(f"{op}_hop_dcn", block_bytes, axes=dcn_axes,
                             group_size=2, overlappable=overlappable)
                    counts["dcn"] += 1
        return counts

    def wire_bytes_total(self) -> float:
        return sum(e["wire_bytes"] for e in self.entries)

    def payload_bytes_total(self) -> float:
        return sum(e["payload_bytes"] for e in self.entries)

    def overlappable_wire_bytes(self) -> float:
        return sum(e["wire_bytes"] for e in self.entries
                   if e["overlappable"])

    def exposed_wire_bytes(self) -> float:
        return sum(e["wire_bytes"] for e in self.entries
                   if not e["overlappable"])

    def seconds(self, link: Optional[LinkModel] = None) -> float:
        link = link or LinkModel()
        return sum(link.seconds(e["wire_bytes"], e["axes"])
                   for e in self.entries)

    def _entry_split(self, e: Dict[str, Any], link: LinkModel
                     ) -> Tuple[str, float, float]:
        """ONE owner of the α+β exposure rule, shared by
        :meth:`overlap_split` and :meth:`overlap_split_by_class`:
        returns ``(link_class, hideable_s, always_exposed_s)`` for one
        entry. A non-overlappable dispatch is fully exposed; an
        overlappable one hides only its bandwidth term — per-dispatch
        setup latency (α) is fabric round-trip time pipelining cannot
        absorb."""
        s = link.seconds(e["wire_bytes"], e["axes"])
        cls = link.link_class(e["axes"])
        if not e["overlappable"]:
            return cls, 0.0, s
        alpha = link.latency(e["axes"]) if s > 0 else 0.0
        return cls, s - alpha, alpha

    def overlap_split(self, link: Optional[LinkModel] = None,
                      compute_s: float = 0.0) -> Dict[str, float]:
        """Split this step's wire time into EXPOSED vs HIDDEN given the
        link model and the compute time available as overlap budget.

        Deterministic model: overlappable entries hide under compute up
        to ``compute_s`` total (the latency-hiding scheduler cannot
        conjure more independent compute than the step has);
        non-overlappable entries are always exposed. Under an α+β link
        model only the BANDWIDTH term of an overlappable dispatch is
        hideable — per-dispatch latency is fabric/setup round-trip time
        that pipelining cannot absorb, so every dispatch's α counts as
        exposed (this is what makes bucket COUNT a real cost on
        latency-dominated DCN links; with the default zero latencies it
        changes nothing). Returns ``{"serial_s", "hideable_s",
        "hidden_s", "exposed_s"}`` with ``serial_s == hidden_s +
        exposed_s`` exactly."""
        link = link or LinkModel()
        hideable = 0.0
        base_exposed = 0.0
        for e in self.entries:
            _cls, h, x = self._entry_split(e, link)
            hideable += h
            base_exposed += x
        hidden = min(hideable, max(0.0, float(compute_s)))
        return {"serial_s": hideable + base_exposed,
                "hideable_s": hideable,
                "hidden_s": hidden,
                "exposed_s": base_exposed + (hideable - hidden)}

    def overlap_split_by_class(self, link: Optional[LinkModel] = None,
                               compute_s: float = 0.0
                               ) -> Dict[str, Dict[str, float]]:
        """The :meth:`overlap_split` attribution broken out PER LINK
        CLASS (``"ici"`` vs ``"dcn"``), so a cross-slice DCN overlap
        regression is nameable as such instead of collapsing into one
        exposed-comm number. The hidden budget (what compute can
        absorb) is allocated to each class proportionally to its
        hideable wire time — deterministic, and the class figures sum
        to the aggregate split's ``hidden_s``/``exposed_s`` exactly up
        to float addition."""
        link = link or LinkModel()
        hideable = {"ici": 0.0, "dcn": 0.0}
        base_exposed = {"ici": 0.0, "dcn": 0.0}
        for e in self.entries:
            cls, h, x = self._entry_split(e, link)
            hideable[cls] += h
            base_exposed[cls] += x
        total_hideable = hideable["ici"] + hideable["dcn"]
        hidden_total = min(total_hideable, max(0.0, float(compute_s)))
        out: Dict[str, Dict[str, float]] = {}
        for cls in ("ici", "dcn"):
            share = (hideable[cls] / total_hideable
                     if total_hideable > 0 else 0.0)
            hidden = hidden_total * share
            out[cls] = {
                "serial_s": hideable[cls] + base_exposed[cls],
                "hideable_s": hideable[cls],
                "hidden_s": hidden,
                "exposed_s": base_exposed[cls] + (hideable[cls] - hidden),
            }
        return out

    def by_op(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for e in self.entries:
            out[e["op"]] = out.get(e["op"], 0.0) + e["wire_bytes"]
        return out


class StepCost:
    """One compiled step's deterministic cost: program FLOPs + HBM
    bytes + wire traffic -> roofline verdict, time lower bound, MFU."""

    def __init__(self, flops: float, hbm_bytes: float = 0.0,
                 traffic: Optional[CollectiveTraffic] = None,
                 link: Optional[LinkModel] = None,
                 peak_flops: Optional[float] = None,
                 hbm_bps: Optional[float] = None):
        if peak_flops is None or hbm_bps is None:
            p, h, self.chip = chip_peak()
            peak_flops = peak_flops if peak_flops is not None else p
            hbm_bps = hbm_bps if hbm_bps is not None else h
        else:
            self.chip = "caller-supplied"
        self.flops = float(flops)
        self.hbm_bytes = float(hbm_bytes)
        self.traffic = traffic or CollectiveTraffic()
        self.link = link or LinkModel()
        self.peak_flops = float(peak_flops)
        self.hbm_bps = float(hbm_bps)

    def compute_s(self) -> float:
        return self.flops / self.peak_flops if self.peak_flops else 0.0

    def memory_s(self) -> float:
        return self.hbm_bytes / self.hbm_bps if self.hbm_bps else 0.0

    def network_s(self) -> float:
        return self.traffic.seconds(self.link)

    def overlap(self) -> Dict[str, float]:
        """The exposed/hidden wire-time split under this step's own
        compute budget (``CollectiveTraffic.overlap_split``)."""
        return self.traffic.overlap_split(self.link, self.compute_s())

    def exposed_network_s(self) -> float:
        """Wire time that actually EXTENDS the step: non-overlappable
        collectives plus whatever overlappable wire time exceeds the
        compute available to hide it."""
        return self.overlap()["exposed_s"]

    def exposed_network_by_class(self) -> Dict[str, float]:
        """Exposed wire time split by link class:
        ``{"ici": s, "dcn": s}`` (``overlap_split_by_class`` under this
        step's own compute budget) — the per-class lane perf_doctor
        reports next to the aggregate exposed-comm %."""
        split = self.traffic.overlap_split_by_class(
            self.link, self.compute_s())
        return {cls: split[cls]["exposed_s"] for cls in ("ici", "dcn")}

    def exposed_comm_fraction(self) -> float:
        """Exposed wire time as a fraction of the modeled step
        (``exposed / (max(compute, memory) + exposed)``) — the number
        perf_doctor reports as exposed-comm %."""
        t = self.step_time_modeled_s()
        return self.exposed_network_s() / t if t > 0 else 0.0

    def step_time_modeled_s(self) -> float:
        """Schedule-aware step-time model: compute (or HBM, whichever
        binds) runs back-to-back while overlappable collectives hide
        under it; only EXPOSED wire time extends the step. This is the
        cost x rate number the scaling-efficiency gate compares across
        chip counts — deterministic, no wall clock anywhere."""
        return max(self.compute_s(), self.memory_s()) \
            + self.exposed_network_s()

    def step_time_lower_bound_s(self) -> float:
        """Perfect-overlap model: the step cannot run faster than its
        slowest resource."""
        return max(self.compute_s(), self.memory_s(), self.network_s())

    def bound(self) -> str:
        times = {"compute": self.compute_s(), "memory": self.memory_s(),
                 "network": self.network_s()}
        return max(times, key=times.get)

    def arithmetic_intensity(self) -> Optional[float]:
        if not self.hbm_bytes:
            return None
        return self.flops / self.hbm_bytes

    def ridge_point(self) -> float:
        """FLOP/byte where the chip flips memory- to compute-bound."""
        return self.peak_flops / self.hbm_bps if self.hbm_bps else 0.0

    def mfu(self, measured_step_s: float) -> Optional[float]:
        """Model FLOPs utilization against the chip peak for a measured
        step time (the ONE place a wall clock enters — supplied by the
        caller, typically a metrics-plane step record)."""
        if measured_step_s <= 0 or not self.peak_flops:
            return None
        return self.flops / (self.peak_flops * measured_step_s)

    def roofline(self) -> Dict[str, Any]:
        ai = self.arithmetic_intensity()
        ov = self.overlap()
        by_class = self.exposed_network_by_class()
        return {
            "exposed_network_ici_s": by_class["ici"],
            "exposed_network_dcn_s": by_class["dcn"],
            "flops": self.flops,
            "hbm_bytes": self.hbm_bytes,
            "wire_bytes": self.traffic.wire_bytes_total(),
            "compute_s": self.compute_s(),
            "memory_s": self.memory_s(),
            "network_s": self.network_s(),
            "exposed_network_s": ov["exposed_s"],
            "hidden_network_s": ov["hidden_s"],
            "exposed_comm_fraction": self.exposed_comm_fraction(),
            "step_time_modeled_s": self.step_time_modeled_s(),
            "step_time_lower_bound_s": self.step_time_lower_bound_s(),
            "bound": self.bound(),
            "arithmetic_intensity": ai,
            "ridge_point": self.ridge_point(),
            "chip": self.chip,
        }


def pipeline_bubble_fraction(pp: int, microbatches: int,
                             virtual_stages: int = 1) -> float:
    """Idle-fraction of the 1F1B pipeline schedule as a multiple of the
    useful compute: ``(p - 1) / (v * m)`` — the Megatron interleaved-VPP
    figure (non-interleaved at v=1 is the classic ``(p-1)/m``). With
    ``v`` virtual stages per device each warmup/cooldown slot costs
    ``1/v`` of a full stage, which is exactly why the ladder's pp>=8
    rungs need interleaving to clear the efficiency gate."""
    p, m, v = int(pp), int(microbatches), int(virtual_stages)
    if p <= 1:
        return 0.0
    if m < 1 or v < 1:
        raise ValueError(
            f"pipeline_bubble_fraction: microbatches={m} and "
            f"virtual_stages={v} must be >= 1")
    return (p - 1) / float(v * m)


def chip_hbm_gb(device=None) -> float:
    """HBM capacity (GB) of ``device`` (default: jax device 0), from
    the generation table; ``PADDLE_HBM_CAPACITY_GB`` overrides, CPU /
    unknown falls back to the v5e 16 GB figure."""
    env = os.environ.get("PADDLE_HBM_CAPACITY_GB")
    if env:
        return float(env)
    kind = ""
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        kind = (getattr(device, "device_kind", "") or "").lower()
    except Exception:
        pass
    for key, gb in CHIP_HBM_GB.items():
        if key in kind:
            return gb
    return _DEFAULT_HBM_GB


class PhasedStepCost:
    """A step modeled as a SEQUENCE of roofline phases.

    One :class:`StepCost` folds the whole program into a single
    ``max(compute, memory)`` — fine for matmul-dominated fwd+bwd, but
    it hides serial tails whose binding resource differs: the
    optimizer update is HBM-bound and runs strictly AFTER the last
    gradient; remat recompute is extra backward work the matmul phase
    cannot absorb. Each phase is its own roofline and the step is the
    SUM — the accounting the single-chip speed gate and the
    perf_doctor MFU lane read."""

    def __init__(self):
        self.phases: List[Tuple[str, StepCost]] = []

    def add(self, name: str, cost: StepCost) -> "PhasedStepCost":
        self.phases.append((name, cost))
        return self

    def step_time_modeled_s(self) -> float:
        return sum(c.step_time_modeled_s() for _, c in self.phases)

    def flops(self) -> float:
        return sum(c.flops for _, c in self.phases)

    def hbm_bytes(self) -> float:
        return sum(c.hbm_bytes for _, c in self.phases)

    def mfu_modeled(self) -> Optional[float]:
        """Model FLOPs over the chip peak for the MODELED step time —
        the deterministic MFU ceiling of this program shape (the
        number the perf_doctor MFU lane aggregates). Uses the FIRST
        phase's peak (phases share a chip)."""
        t = self.step_time_modeled_s()
        if not self.phases or t <= 0:
            return None
        peak = self.phases[0][1].peak_flops
        return self.flops() / (peak * t) if peak else None

    def breakdown(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for name, c in self.phases:
            out[name] = {
                "flops": c.flops, "hbm_bytes": c.hbm_bytes,
                "compute_s": c.compute_s(), "memory_s": c.memory_s(),
                "step_time_modeled_s": c.step_time_modeled_s(),
                "bound": c.bound()}
        return out

    def step_record_fields(self) -> Dict[str, float]:
        """The metrics-plane step-record lane: stamp these through
        ``metrics.step_end(**fields)`` and ``perf_doctor`` renders the
        MFU/roofline columns (aggregated only when every rank carries
        them)."""
        peak = self.phases[0][1].peak_flops if self.phases else 0.0
        return {"modeled_step_s": self.step_time_modeled_s(),
                "roofline_s": self.step_time_modeled_s(),
                "modeled_flops": self.flops(),
                "peak_flops": peak}


def step_cost_of_program(program, link: Optional[LinkModel] = None
                         ) -> Optional[StepCost]:
    """Build a :class:`StepCost` from a
    :class:`~paddle2_tpu.jit.train_step.TrainStepProgram` that ran with
    ``collect_cost = True`` (its last fresh build stashed the lowered
    cost analysis and abstract call args)."""
    entry = getattr(program, "last_entry", None)
    aargs = getattr(program, "last_abstract_args", None)
    if entry is None or aargs is None:
        return None
    ca = program_cost(entry, aargs)
    if not ca:
        return None
    return StepCost(flops=ca.get("flops", 0.0),
                    hbm_bytes=ca.get("bytes accessed", 0.0),
                    link=link)


__all__ = ["CHIP_PEAKS", "CHIP_HBM_GB", "chip_peak", "chip_hbm_gb",
           "cost_analysis_of", "program_cost",
           "abstractify", "wire_bytes", "sparse_transfer_seconds",
           "LinkModel", "CollectiveTraffic",
           "StepCost", "PhasedStepCost", "step_cost_of_program",
           "pipeline_bubble_fraction",
           "DEFAULT_ICI_GBPS", "DEFAULT_DCN_GBPS",
           "DEFAULT_ICI_LATENCY_US", "DEFAULT_DCN_LATENCY_US",
           "DEFAULT_HOST_GBPS", "HOST_ENV", "host_link_bps",
           "PEAK_ENV", "HBM_ENV", "ICI_ENV", "DCN_ENV", "DCN_AXES_ENV",
           "ICI_LATENCY_ENV", "DCN_LATENCY_ENV"]
