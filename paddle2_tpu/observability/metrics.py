"""Always-on metrics plane: typed registry + per-rank JSONL stream.

The flight recorder (PR 3) answers "what happened when it died"; this
module answers "where does the step time go while it lives". It is the
third leg of the observability stack next to the profiler (deep, scoped
traces) and the flight ring (post-mortem evidence): cheap, structured,
ALWAYS-ON telemetry the ``perf_doctor`` CLI and CI gates read.

Three metric kinds, Prometheus-shaped (the reference framework's
monitor/stat registry analog):

* :class:`Counter` — monotonically increasing totals (steps, retries,
  collective bytes, SDC convictions, compile-cache hits);
* :class:`Gauge` — last-written values (loss scale, program-cache
  size);
* :class:`Histogram` — bucketed distributions (checkpoint-save
  seconds, compile seconds).

All three carry labels (``inc("collectives_total", op="all_reduce")``).

**Step windows.** The plane slices wall time into consecutive *step
windows*: everything between two ``step_end()`` calls belongs to one
step, and instrumented spans inside the window (:func:`phase`) classify
it — ``input`` (dataloader wait), ``compute`` (the dispatched step
program), ``collective`` (eager collective dispatch+wait). The
remainder is ``host`` (python bookkeeping). Because ``host`` is the
residual and phases attribute time to the INNERMOST open phase only,
the four components sum to the recorded total *exactly* — the invariant
``bench.py --observability`` gates on. Every step window is written as
one ``{"type": "step", ...}`` record in the JSONL stream.

**Overhead contract** (same discipline as ``flight_recorder`` /
``chaos``): when the plane is off every hook is ONE module-attribute
load (``if _ACTIVE is None: return``) — no locks, no allocation, no
device syncs. When on, an event is a dict upsert on preallocated
structures; writes are buffered and flushed every
``PADDLE_METRICS_FLUSH_STEPS`` windows (never inside a phase). The
bench gates overhead by *deterministic record accounting* — events per
step x a conservative per-event host-op cost against the step's XLA
cost_analysis FLOPs — not wall-clock A/B (unreliable in shared
sandboxes).

Enable by setting ``PADDLE_METRICS_DIR`` (the launcher forwards it to
every worker; auto-enables on workers exactly like the flight
recorder's ``PADDLE_TRAINER_ID`` guard) or explicitly::

    from paddle2_tpu.observability import metrics
    metrics.enable("/tmp/metrics")
    ... train ...
    metrics.flush()              # JSONL snapshot + step records
    metrics.export_prometheus()  # textfile-collector .prom sibling
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from contextlib import nullcontext
from typing import Any, Dict, List, Optional, Tuple

METRICS_DIR_ENV = "PADDLE_METRICS_DIR"
METRICS_FLUSH_ENV = "PADDLE_METRICS_FLUSH_STEPS"

_DEFAULT_FLUSH_STEPS = 50

# hard cap on records held across failed flushes: a persistently
# unwritable metrics dir (disk full, dir deleted) must never grow the
# buffer — and the training process — without bound
_MAX_BUFFER_RECORDS = 10_000

# conservative host-op-equivalent cost of ONE metric event (a dict
# upsert + float add + tuple hash: high hundreds of ns on a laptop
# core, charged here as generic "ops" so the overhead gate can compare
# events-per-step x cost against step FLOPs deterministically, without
# wall clocks). Deliberately pessimistic: a gate that passes with this
# constant passes on real hardware with margin.
EVENT_COST_OPS = 5000.0

# step-window phase names (everything else lands in the "host" residual)
PHASES = ("input", "compute", "collective")

_HIST_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, float("inf"))


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_str: str = ""):
        self.name = name
        self.help = help_str
        self.values: Dict[Tuple, float] = {}

    def labels_list(self) -> List[Tuple[Tuple, float]]:
        return sorted(self.values.items())

    def snapshot(self) -> Dict[str, float]:
        return {_fmt_labels(k): v for k, v in self.labels_list()}


class Counter(_Metric):
    """Monotonic total. ``inc`` with negative amounts raises."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = _label_key(labels)
        self.values[key] = self.values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self.values.get(_label_key(labels), 0.0)


class Gauge(_Metric):
    """Last-written value."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        self.values[_label_key(labels)] = float(value)

    def value(self, **labels) -> float:
        return self.values.get(_label_key(labels), 0.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations <= its upper bound; +Inf == count)."""

    kind = "histogram"

    def __init__(self, name: str, help_str: str = "",
                 buckets: Tuple[float, ...] = _HIST_BUCKETS):
        super().__init__(name, help_str)
        self.buckets = tuple(buckets)
        if self.buckets[-1] != float("inf"):
            self.buckets = self.buckets + (float("inf"),)
        # per-labelset: [counts per bucket], sum, count
        self.series: Dict[Tuple, Dict[str, Any]] = {}

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        s = self.series.get(key)
        if s is None:
            s = {"counts": [0] * len(self.buckets), "sum": 0.0,
                 "count": 0}
            self.series[key] = s
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                s["counts"][i] += 1
        s["sum"] += float(value)
        s["count"] += 1

    def labels_list(self):
        return sorted(self.series.items())

    def snapshot(self) -> Dict[str, Any]:
        # per-bucket CUMULATIVE counts ride the JSONL snapshot too (the
        # Prometheus export always had them): sum/count alone cannot
        # reconstruct percentiles downstream, which left perf_doctor
        # without p50/p99 lanes. The +Inf upper bound serializes as
        # None — a bare Infinity literal breaks strict-JSON consumers.
        return {_fmt_labels(k): {
            "sum": s["sum"], "count": s["count"],
            "buckets": [None if ub == float("inf") else ub
                        for ub in self.buckets],
            "counts": list(s["counts"]),
        } for k, s in self.labels_list()}


def _fmt_labels(key: Tuple) -> str:
    if not key:
        return ""
    return ",".join(f'{k}="{v}"' for k, v in key)


# Reusable no-op context for disabled-plane phase() calls.
_NULL_PHASE = nullcontext()


class _Phase:
    __slots__ = ("_plane", "_name")

    def __init__(self, plane: "MetricsPlane", name: str):
        self._plane = plane
        self._name = name

    def __enter__(self):
        self._plane.phase_enter(self._name)
        return self

    def __exit__(self, *exc):
        self._plane.phase_exit()
        return False


class MetricsPlane:
    """Per-rank metric registry + step-window clock + JSONL writer."""

    def __init__(self, directory: str, rank: Optional[int] = None,
                 flush_steps: Optional[int] = None):
        if rank is None:
            try:
                from ..distributed.env import get_rank
                rank = int(get_rank())
            except Exception:
                rank = 0
        self.dir = directory
        self.rank = int(rank)
        if flush_steps is None:
            try:
                flush_steps = int(os.environ.get(
                    METRICS_FLUSH_ENV, _DEFAULT_FLUSH_STEPS))
            except ValueError:
                flush_steps = _DEFAULT_FLUSH_STEPS
        self.flush_steps = max(1, int(flush_steps))
        self._metrics: Dict[str, _Metric] = {}
        self._mu = threading.RLock()
        self._buffer: List[str] = []
        # step-window state: wall-clock origin of the current window,
        # the innermost-phase stack, and per-phase accumulators
        self._win_t0 = time.perf_counter()
        self._stack: List[List] = []      # [name, segment_start]
        self._phases: Dict[str, float] = {}
        self._step_no = 0
        # deterministic overhead accounting: every metric event (inc /
        # set / observe / phase pair / step record) bumps this — the
        # bench multiplies by EVENT_COST_OPS instead of timing
        self.events_recorded = 0

    # -- registry --------------------------------------------------------
    def _get(self, name: str, cls, help_str: str = "") -> _Metric:
        m = self._metrics.get(name)
        if m is None:
            with self._mu:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, help_str)
                    self._metrics[name] = m
        elif not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{m.kind}, not {cls.kind}")
        return m

    def counter(self, name: str, help_str: str = "") -> Counter:
        return self._get(name, Counter, help_str)

    def gauge(self, name: str, help_str: str = "") -> Gauge:
        return self._get(name, Gauge, help_str)

    def histogram(self, name: str, help_str: str = "") -> Histogram:
        return self._get(name, Histogram, help_str)

    def inc(self, name: str, amount: float = 1.0, **labels) -> None:
        # _mu (reentrant) serializes writers against the flush/snapshot
        # iteration in step_end/export: background threads (health
        # prober, watchdog) inc concurrently with the training thread,
        # and an unguarded label upsert during a snapshot's
        # sorted(values.items()) would raise out of step_end
        with self._mu:
            self.events_recorded += 1
            self.counter(name).inc(amount, **labels)

    def set_gauge(self, name: str, value: float, **labels) -> None:
        with self._mu:
            self.events_recorded += 1
            self.gauge(name).set(value, **labels)

    def observe(self, name: str, value: float, **labels) -> None:
        with self._mu:
            self.events_recorded += 1
            self.histogram(name).observe(value, **labels)

    # -- step windows ----------------------------------------------------
    def phase(self, name: str) -> _Phase:
        return _Phase(self, name)

    def phase_enter(self, name: str) -> None:
        """Open a phase span. Time is attributed to the INNERMOST open
        phase only (an eager collective inside the compute span moves
        its wall time from compute to collective), which keeps the
        per-phase spans disjoint — the exact-sum invariant depends on
        it."""
        now = time.perf_counter()
        with self._mu:
            if self._stack:
                parent = self._stack[-1]
                self._phases[parent[0]] = self._phases.get(
                    parent[0], 0.0) + (now - parent[1])
                # reset the parent's segment origin: its pre-child span
                # is credited, so an abnormal close (step_end draining
                # a still-open stack) must not re-credit it
                parent[1] = now
            self._stack.append([name, now])

    def phase_exit(self) -> None:
        now = time.perf_counter()
        with self._mu:
            if not self._stack:
                return
            name, seg = self._stack.pop()
            self._phases[name] = self._phases.get(name, 0.0) + (now - seg)
            if self._stack:
                self._stack[-1][1] = now
            self.events_recorded += 1

    def step_end(self, tokens: Optional[int] = None,
                 samples: Optional[int] = None,
                 loss_scale: Optional[float] = None,
                 **extra) -> Dict[str, Any]:
        """Close the current step window and open the next one. Writes
        one ``{"type": "step"}`` record whose four components sum to
        ``total_s`` exactly (``host_s`` is the residual)."""
        now = time.perf_counter()
        with self._mu:
            # close any phase still open (defensive: an exception path
            # that skipped a phase_exit must not leak into forever).
            # Only the INNERMOST frame holds unattributed time: enter
            # and exit both reset the parent's segment origin when a
            # child takes over, so outer frames are fully credited
            if self._stack:
                name, seg = self._stack[-1]
                self._phases[name] = self._phases.get(
                    name, 0.0) + (now - seg)
                self._stack = []
            total = now - self._win_t0
            comp = {p: self._phases.get(p, 0.0) for p in PHASES}
            other = sum(v for k, v in self._phases.items()
                        if k not in PHASES)
            host = total - sum(comp.values()) - other
            rec: Dict[str, Any] = {
                "type": "step", "t": time.time(), "rank": self.rank,
                "step": self._step_no, "total_s": total,
                "input_wait_s": comp["input"],
                "compute_s": comp["compute"],
                "collective_s": comp["collective"],
                "host_s": host + other,
            }
            if tokens is not None:
                rec["tokens"] = int(tokens)
                if total > 0:
                    rec["tokens_per_s"] = tokens / total
            if samples is not None:
                rec["samples"] = int(samples)
            if loss_scale is not None:
                rec["loss_scale"] = float(loss_scale)
            rec.update(extra)
            self._buffer.append(json.dumps(rec))
            self._step_no += 1
            self._phases = {}
            self._win_t0 = time.perf_counter()
            self.events_recorded += 1
            self.inc("steps_total")
            if self._step_no % self.flush_steps == 0:
                self._flush_locked(snapshot=True)
        return rec

    def step_window_reset(self) -> None:
        """Re-open the step window NOW, discarding time accrued since
        the last ``step_end``. Loop drivers call this at epoch
        boundaries: eval passes, callbacks, and checkpoint saves run
        between the last step of epoch N and the first step of epoch
        N+1, and without a reset all of it lands in that first step's
        ``host_s`` — a many-second outlier that corrupts perf_doctor
        means (warmup exclusion only drops the first record per RANK,
        not per epoch). No record is written; open phases are
        discarded with the window."""
        with self._mu:
            self._phases = {}
            self._stack = []
            self._win_t0 = time.perf_counter()

    @property
    def step_no(self) -> int:
        return self._step_no

    # -- output ----------------------------------------------------------
    @property
    def stream_path(self) -> str:
        return os.path.join(self.dir, f"metrics_rank_{self.rank}.jsonl")

    @property
    def prom_path(self) -> str:
        return os.path.join(self.dir, f"metrics_rank_{self.rank}.prom")

    def snapshot(self) -> Dict[str, Any]:
        """Every registered metric's current values, JSON-shaped."""
        with self._mu:
            out: Dict[str, Any] = {"counters": {}, "gauges": {},
                                   "histograms": {}}
            for name, m in sorted(self._metrics.items()):
                slot = {"counter": "counters", "gauge": "gauges",
                        "histogram": "histograms"}[m.kind]
                out[slot][name] = m.snapshot()
            return out

    def _flush_locked(self, snapshot: bool = False) -> None:
        if snapshot:
            rec = {"type": "metrics", "t": time.time(),
                   "rank": self.rank, "step": self._step_no}
            rec.update(self.snapshot())
            self._buffer.append(json.dumps(rec))
        if not self._buffer:
            return
        lines, self._buffer = self._buffer, []
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(self.stream_path, "a") as f:
                f.write("\n".join(lines) + "\n")
        except OSError:
            # telemetry is best-effort, never a failure source — keep
            # the records for the next flush attempt, bounded (oldest
            # dropped first)
            self._buffer = (lines + self._buffer)[-_MAX_BUFFER_RECORDS:]

    def flush(self, snapshot: bool = True) -> None:
        with self._mu:
            self._flush_locked(snapshot=snapshot)

    def export_prometheus(self, path: Optional[str] = None) -> str:
        """Write the registry in Prometheus text exposition format (the
        node_exporter textfile-collector contract) and return the
        path."""
        with self._mu:
            lines: List[str] = []
            for name, m in sorted(self._metrics.items()):
                if m.help:
                    lines.append(f"# HELP {name} {m.help}")
                lines.append(f"# TYPE {name} {m.kind}")
                if isinstance(m, Histogram):
                    for key, s in m.labels_list():
                        base = _fmt_labels(key)
                        cum = 0
                        for ub, c in zip(m.buckets, s["counts"]):
                            cum = c
                            le = "+Inf" if ub == float("inf") else repr(ub)
                            lbl = (base + "," if base else "") + \
                                f'le="{le}"'
                            lines.append(
                                f"{name}_bucket{{{lbl}}} {cum}")
                        lines.append(
                            f"{name}_sum{{{base}}} {s['sum']}"
                            if base else f"{name}_sum {s['sum']}")
                        lines.append(
                            f"{name}_count{{{base}}} {s['count']}"
                            if base else f"{name}_count {s['count']}")
                else:
                    for key, v in m.labels_list():
                        base = _fmt_labels(key)
                        lines.append(f"{name}{{{base}}} {v}"
                                     if base else f"{name} {v}")
            text = "\n".join(lines) + "\n"
        out = path or self.prom_path
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, out)
        return out


# ---------------------------------------------------------------- module
_ACTIVE: Optional[MetricsPlane] = None
_atexit_installed = False


def enable(directory: Optional[str] = None, rank: Optional[int] = None,
           flush_steps: Optional[int] = None) -> MetricsPlane:
    """Turn the metrics plane on for this process. ``directory``
    defaults to ``PADDLE_METRICS_DIR``. Idempotent per directory."""
    global _ACTIVE, _atexit_installed
    d = directory or os.environ.get(METRICS_DIR_ENV)
    if not d:
        raise ValueError(
            f"metrics plane needs a directory: pass one or set "
            f"{METRICS_DIR_ENV}")
    prev = _ACTIVE
    if prev is not None:
        if prev.dir == d and (rank is None or rank == prev.rank):
            # idempotent: keep counters + buffer, but honor an explicit
            # flush cadence — the auto-enabled plane defaults to a lazy
            # cadence, and a caller asking for flush_steps=1 wants
            # per-step durability, not the old setting.
            if flush_steps is not None:
                # same clamp as the constructor: flush_steps=0 must
                # mean "every step", not a ZeroDivisionError in step_end
                prev.flush_steps = max(1, int(flush_steps))
            return prev
        try:
            prev.flush()           # don't drop the old plane's records
        except Exception:
            pass
    _ACTIVE = MetricsPlane(d, rank=rank, flush_steps=flush_steps)
    if not _atexit_installed:
        _atexit_installed = True
        atexit.register(_atexit_flush)
    return _ACTIVE


def disable() -> None:
    """Flush and stop recording."""
    global _ACTIVE
    pl, _ACTIVE = _ACTIVE, None
    if pl is not None:
        try:
            pl.flush()
        except Exception:
            pass


def active() -> Optional[MetricsPlane]:
    return _ACTIVE


def _atexit_flush() -> None:
    pl = _ACTIVE
    if pl is not None:
        try:
            pl.flush()
            pl.export_prometheus()
        except Exception:
            pass


# -- hot-path hooks (the one-attribute-load contract) --------------------
def inc(name: str, amount: float = 1.0, **labels) -> None:
    pl = _ACTIVE
    if pl is None:
        return
    pl.inc(name, amount, **labels)


def set_gauge(name: str, value: float, **labels) -> None:
    pl = _ACTIVE
    if pl is None:
        return
    pl.set_gauge(name, value, **labels)


def observe(name: str, value: float, **labels) -> None:
    pl = _ACTIVE
    if pl is None:
        return
    pl.observe(name, value, **labels)


def phase(name: str):
    pl = _ACTIVE
    if pl is None:
        return _NULL_PHASE
    return pl.phase(name)


def step_end(**kwargs) -> Optional[Dict[str, Any]]:
    pl = _ACTIVE
    if pl is None:
        return None
    return pl.step_end(**kwargs)


def flush() -> None:
    pl = _ACTIVE
    if pl is not None:
        pl.flush()


def export_prometheus(path: Optional[str] = None) -> Optional[str]:
    pl = _ACTIVE
    if pl is None:
        return None
    return pl.export_prometheus(path)


# auto-enable: the launcher (or operator) sets PADDLE_METRICS_DIR for
# the gang; the PADDLE_TRAINER_ID guard keeps an operator shell running
# perf_doctor against the same env from masquerading as rank 0 (the
# same posture as flight_recorder's auto-enable)
if os.environ.get(METRICS_DIR_ENV) and os.environ.get("PADDLE_TRAINER_ID"):
    try:
        enable(os.environ[METRICS_DIR_ENV])
    except (OSError, ValueError):
        pass


__all__ = ["Counter", "Gauge", "Histogram", "MetricsPlane", "enable",
           "disable", "active", "inc", "set_gauge", "observe", "phase",
           "step_end", "flush", "export_prometheus", "METRICS_DIR_ENV",
           "METRICS_FLUSH_ENV", "EVENT_COST_OPS", "PHASES"]
