"""Request-lifecycle tracing: per-request span trees for the serving
fleet, with exact tail-latency decomposition.

PR 7 gave *training* an exact step-time decomposition
(``input_wait + compute + collective + host == total``); this module
gives every serving REQUEST the same discipline. The serving stack
(scheduler / engine / router / hot-swap controller) records lifecycle
events keyed by a stable per-request **trace id** — submit,
queue-wait, admission, prefill, per-round decode, eviction/requeue,
failover-adopt, hot-swap pause, finish — and the decomposition pass
(:func:`decompose`) turns each finished request's event list into the
Dapper-style component split the ``serve_doctor`` CLI attributes tails
with::

    queue_wait + prefill + decode_compute + eviction_stall
        + failover_stall + swap_stall + host == e2e latency

``host`` is the residual (scheduling gaps, lockstep rounding), the
same rule step windows use. The sum is EXACT — not to a tolerance —
because the decomposition does its interval arithmetic in **integer
picoseconds** (:data:`PS_PER_S`): every timestamp is quantized once,
intervals telescope on shared stamps, and the residual closes the sum
by construction, so a nonnegative ``host`` plus nonnegative components
IS the proof that no interval was double-counted or lost. The bench
gates this on every finished request of the PR 11 chaos drills.

Clock discipline (the PR 9/11 posture): time enters ONLY through the
caller-supplied ``t=`` stamps. The discrete-event simulators pass
their virtual cost-model clock — traces, decompositions, and the
``TRACING_r01.json`` artifact are bit-stable across runs — while a
live engine passes wall clock and gets the same span tree with real
timestamps.

Overhead contract (the metrics/flight_recorder discipline): when the
plane is off, every module-level hook is ONE module-attribute load
(``if _ACTIVE is None: return``). When on, an event is a dict + list
append; overhead is gated by deterministic record accounting —
events x :data:`~paddle2_tpu.observability.metrics.EVENT_COST_OPS`
against step FLOPs — never wall-clock A/B.

Outputs:

* per-rank JSONL stream ``PADDLE_TRACE_DIR/trace_rank_N.jsonl``
  (``{"type": "span", "event": ..., "tid": ..., "t": ...}`` records,
  no wall-clock fields — byte-stable);
* :meth:`TracePlane.export_chrome_trace` — a ``chrome://tracing`` /
  Perfetto view (one lane per engine, one track per request) that
  correlates with the profiler's merged traces and the flight ring:
  all three timelines share the ``reliability.flight_record`` event
  names (admit / evict / requeue / decode_step / adopt / hot_swap).

Enable with ``PADDLE_TRACE_DIR`` (+ the ``PADDLE_TRAINER_ID`` guard,
exactly like the metrics plane) or explicitly::

    from paddle2_tpu.observability import tracing
    tracing.enable("/tmp/traces")
    ... serve ...
    tracing.flush()
    tracing.active().export_chrome_trace()
"""

from __future__ import annotations

import atexit
import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

TRACE_DIR_ENV = "PADDLE_TRACE_DIR"
TRACE_FLUSH_ENV = "PADDLE_TRACE_FLUSH_EVENTS"
TRACE_MAX_EVENTS_ENV = "PADDLE_TRACE_MAX_EVENTS"

_DEFAULT_FLUSH_EVENTS = 512
# same bounded-buffer posture as the metrics plane: an unwritable dir
# must never grow the process without bound
_MAX_BUFFER_RECORDS = 100_000
# in-memory retention for export_chrome_trace()/in-process decompose:
# newest N events (a live engine serving for days must not grow RSS
# without bound; the JSONL stream is the durable full record)
_DEFAULT_MAX_EVENTS = 200_000

# integer-picosecond quantum for the exact decomposition: fine enough
# that a 1-ulp float difference at second scale (~2e-16 s) can never
# move a boundary, coarse enough that clocks up to ~2.5 hours stay
# exactly representable in the 53-bit mantissa on the way in
PS_PER_S = 10 ** 12

# decomposition components, canonical order (host is the residual).
# spill_fetch_s: KV-tier promotion stalls (host-link / peer-DCN
# fetches at admission); migration_stall_s: failover KV migration
# transfers (ISSUE 16) — both exact intervals, not residuals.
COMPONENTS = ("queue_wait_s", "prefill_s", "decode_compute_s",
              "eviction_stall_s", "failover_stall_s", "swap_stall_s",
              "spill_fetch_s", "migration_stall_s", "host_s")

# which waiting-interval cause feeds which component
_WAIT_COMPONENT = {"queue": "queue_wait_s", "evict": "eviction_stall_s",
                   "failover": "failover_stall_s"}


def _ps(t: float) -> int:
    return int(round(float(t) * PS_PER_S))


class TracePlane:
    """Per-rank request-lifecycle event recorder + JSONL writer."""

    def __init__(self, directory: str, rank: Optional[int] = None,
                 flush_events: Optional[int] = None):
        if rank is None:
            try:
                from ..distributed.env import get_rank
                rank = int(get_rank())
            except Exception:
                rank = 0
        self.dir = directory
        self.rank = int(rank)
        if flush_events is None:
            try:
                flush_events = int(os.environ.get(
                    TRACE_FLUSH_ENV, _DEFAULT_FLUSH_EVENTS))
            except ValueError:
                flush_events = _DEFAULT_FLUSH_EVENTS
        self.flush_events = max(1, int(flush_events))
        try:
            self.max_events = max(1024, int(os.environ.get(
                TRACE_MAX_EVENTS_ENV, _DEFAULT_MAX_EVENTS)))
        except ValueError:
            self.max_events = _DEFAULT_MAX_EVENTS
        self._mu = threading.RLock()
        self._buffer: List[str] = []
        # in-memory event window (newest max_events) for chrome export
        # / in-process decomposition; the JSONL stream is the durable
        # FULL copy — a long-lived live engine must not grow RSS
        # unboundedly just because tracing is on
        self._events: List[Dict[str, Any]] = []
        self._n = 0
        # deterministic overhead accounting: one bump per recorded
        # event — the bench multiplies by metrics.EVENT_COST_OPS
        self.events_recorded = 0

    # -- recording (hot path) -------------------------------------------
    def event(self, name: str, t: float, tid=None, dur: float = 0.0,
              tids: Optional[List] = None, **fields) -> None:
        """Record one lifecycle event. ``t`` is the caller's clock
        (virtual in the simulators, wall in a live engine); ``tid`` is
        the stable trace id of ONE request, ``tids`` a list when the
        event covers a whole batch (decode steps, engine death). An
        interval event carries ``dur`` — or an explicit ``end=`` field
        when the end stamp must match another event's ``t`` bitwise."""
        rec: Dict[str, Any] = {"type": "span", "event": name,
                               "t": float(t)}
        if tid is not None:
            rec["tid"] = tid
        if tids is not None:
            rec["tids"] = list(tids)
        if dur:
            rec["dur"] = float(dur)
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        with self._mu:
            rec["n"] = self._n           # per-rank causal order
            self._n += 1
            self.events_recorded += 1
            self._events.append(rec)
            if len(self._events) > self.max_events:
                # drop the oldest half in one slice (amortized O(1)
                # per event) — readers needing the full history read
                # the JSONL stream
                del self._events[:self.max_events // 2]
            self._buffer.append(json.dumps(rec))
            if len(self._buffer) >= self.flush_events:
                self._flush_locked()

    # -- introspection ---------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        with self._mu:
            return list(self._events)

    # -- output ----------------------------------------------------------
    @property
    def stream_path(self) -> str:
        return os.path.join(self.dir, f"trace_rank_{self.rank}.jsonl")

    def _flush_locked(self) -> None:
        if not self._buffer:
            return
        lines, self._buffer = self._buffer, []
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(self.stream_path, "a") as f:
                f.write("\n".join(lines) + "\n")
        except OSError:
            self._buffer = (lines + self._buffer)[-_MAX_BUFFER_RECORDS:]

    def flush(self) -> None:
        with self._mu:
            self._flush_locked()

    def export_chrome_trace(self, path: Optional[str] = None) -> str:
        """Write the event list as a chrome://tracing / Perfetto JSON:
        one process lane per engine, one thread track per trace id,
        interval events as ``X`` slices and instants as ``i`` marks.
        The event names match the flight ring and the metrics phases,
        so the three timelines line up in one viewer."""
        out = path or os.path.join(self.dir,
                                   f"trace_rank_{self.rank}.trace.json")
        events = self.events()
        tev: List[Dict[str, Any]] = []
        seen_lanes = set()
        for rec in events:
            pid = int(rec.get("engine", 0) or 0)
            if pid not in seen_lanes:
                seen_lanes.add(pid)
                tev.append({"ph": "M", "pid": pid, "name": "process_name",
                            "args": {"name": f"engine {pid}"}})
            tids = rec.get("tids")
            targets = tids if tids is not None else [rec.get("tid", 0)]
            end = rec.get("end")
            dur = (end - rec["t"]) if end is not None \
                else rec.get("dur", 0.0)
            args = {k: v for k, v in rec.items()
                    if k not in ("type", "event", "t", "dur", "end",
                                 "tid", "tids", "n")}
            for tid in targets:
                base = {"name": rec["event"], "pid": pid,
                        "tid": tid if tid is not None else 0,
                        "ts": rec["t"] * 1e6, "args": args}
                if dur > 0:
                    tev.append({**base, "ph": "X", "dur": dur * 1e6})
                else:
                    tev.append({**base, "ph": "i", "s": "t"})
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"traceEvents": tev}, f)
        os.replace(tmp, out)
        return out


# ---------------------------------------------------------------- module
_ACTIVE: Optional[TracePlane] = None
_atexit_installed = False


def enable(directory: Optional[str] = None, rank: Optional[int] = None,
           flush_events: Optional[int] = None) -> TracePlane:
    """Turn request tracing on for this process. ``directory``
    defaults to ``PADDLE_TRACE_DIR``. Idempotent per directory."""
    global _ACTIVE, _atexit_installed
    d = directory or os.environ.get(TRACE_DIR_ENV)
    if not d:
        raise ValueError(f"tracing needs a directory: pass one or set "
                         f"{TRACE_DIR_ENV}")
    prev = _ACTIVE
    if prev is not None:
        if prev.dir == d and (rank is None or rank == prev.rank):
            if flush_events is not None:
                prev.flush_events = max(1, int(flush_events))
            return prev
        try:
            prev.flush()
        except Exception:
            pass
    _ACTIVE = TracePlane(d, rank=rank, flush_events=flush_events)
    if not _atexit_installed:
        _atexit_installed = True
        atexit.register(_atexit_flush)
    return _ACTIVE


def disable() -> None:
    global _ACTIVE
    pl, _ACTIVE = _ACTIVE, None
    if pl is not None:
        try:
            pl.flush()
        except Exception:
            pass


def active() -> Optional[TracePlane]:
    return _ACTIVE


def _atexit_flush() -> None:
    pl = _ACTIVE
    if pl is not None:
        try:
            pl.flush()
        except Exception:
            pass


# -- hot-path hooks (the one-attribute-load contract) --------------------
def event(name: str, t: float, tid=None, dur: float = 0.0,
          tids: Optional[List] = None, **fields) -> None:
    pl = _ACTIVE
    if pl is None:
        return
    pl.event(name, t, tid=tid, dur=dur, tids=tids, **fields)


def serving_span(fields: Dict[str, Any]) -> None:
    """Adapter for :func:`serving.reliability.flight_record`: every
    serving flight span that carries a clock stamp (``t``) is mirrored
    into the trace stream, so the flight ring and the request traces
    share ONE set of instrumentation sites and event names. Spans
    without a stamp (or with neither ``tid`` nor ``tids``) are
    flight-only."""
    pl = _ACTIVE
    if pl is None:
        return
    f = dict(fields)
    name = f.pop("event", None)
    t = f.pop("t", None)
    tid = f.pop("tid", None)
    tids = f.pop("tids", None)
    if name is None or t is None or (tid is None and tids is None):
        return
    pl.event(name, t, tid=tid, dur=f.pop("dur", 0.0), tids=tids, **f)


def flush() -> None:
    pl = _ACTIVE
    if pl is not None:
        pl.flush()


# ------------------------------------------------------------- assembly
def load_trace_dir(directory: str) -> List[Dict[str, Any]]:
    """Every span record from ``trace_rank_N.jsonl`` files under
    ``directory`` (a single file path is accepted too), merged in
    ``(t, rank, n)`` order. Unparseable lines are skipped."""
    paths: List[Tuple[int, str]] = []
    if os.path.isfile(directory):
        paths.append((0, directory))
    elif os.path.isdir(directory):
        for name in sorted(os.listdir(directory)):
            if name.startswith("trace_rank_") and name.endswith(".jsonl"):
                stem = name[len("trace_rank_"):-len(".jsonl")]
                paths.append((int(stem) if stem.isdigit() else 0,
                              os.path.join(directory, name)))
    records: List[Dict[str, Any]] = []
    for rank, p in paths:
        try:
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("type") == "span":
                        rec["rank"] = rank
                        records.append(rec)
        except OSError:
            continue
    records.sort(key=lambda r: (r.get("t", 0.0), r.get("rank", 0),
                                r.get("n", 0)))
    return records


def assemble(records: List[Dict[str, Any]]) -> Dict[Any, List[dict]]:
    """Group span records per trace id, preserving order. Batch-scoped
    records (``tids`` lists: decode steps, engine death) are expanded
    to every member request."""
    out: Dict[Any, List[dict]] = {}
    for rec in records:
        tids = rec.get("tids")
        if tids is not None:
            for tid in tids:
                out.setdefault(tid, []).append(rec)
        elif "tid" in rec:
            out.setdefault(rec["tid"], []).append(rec)
    return out


# -------------------------------------------------------- decomposition
def decompose_request(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One request's event list (time-ordered) -> its exact latency
    decomposition. All interval arithmetic happens in integer
    picoseconds; ``host_s`` is the residual that closes the sum, and
    ``exact`` asserts the whole invariant: the request finished, every
    component is nonnegative, and the ordered component sum equals the
    e2e latency EXACTLY (integer arithmetic — bitwise stable).

    Waiting intervals are attributed to their CAUSE: submit -> first
    admission is ``queue_wait``; eviction (and block-table-corruption
    requeue) -> re-admission is ``eviction_stall``; engine death ->
    re-admission on the adopter is ``failover_stall`` (probe-detection
    latency included, since the wait starts at the DEATH stamp).
    Prefill spans cover admission -> first-token-ready on the prefill
    lane (lane queueing included — disaggregation means decode never
    waits on it); decode spans are the modeled per-round step costs,
    dropped (chaos-retried) rounds included; ``swap_stall`` sums
    hot-swap pause stamps (zero for the arg-swap engines, a real pause
    for engines that must quiesce)."""
    comps_ps = {c: 0 for c in COMPONENTS}
    submit_ps: Optional[int] = None
    finish_ps: Optional[int] = None
    first_token_ps: Optional[int] = None
    wait_start_ps: Optional[int] = None
    wait_cause = "queue"
    # end/component of the most recent charged work interval: a stall
    # that opens BEFORE it completes (an engine dying mid-prefill, an
    # eviction of a still-prefilling sequence) invalidates the
    # uncompleted tail — that work never happened for this request and
    # must be clipped back out, or the components would overlap the
    # stall and overrun the e2e total
    last_fwd_end_ps: Optional[int] = None
    last_fwd_comp: Optional[str] = None
    counts = {"evictions": 0, "retries": 0, "failovers": 0,
              "corruptions": 0, "swaps": 0, "spill_fetches": 0,
              "migrations": 0}
    shed = False
    error = None
    tokens: Optional[int] = None
    engines = set()

    def _end_ps(rec) -> int:
        if "end" in rec:
            return _ps(rec["end"])
        # t + dur as FLOATS first: the engine computes its finish stamp
        # as the same float sum, so the two quantize identically
        return _ps(rec["t"] + rec.get("dur", 0.0))

    for rec in events:
        name = rec.get("event")
        t_ps = _ps(rec.get("t", 0.0))
        if "engine" in rec:
            engines.add(rec["engine"])
        if name == "submit":
            submit_ps = t_ps
            wait_start_ps = t_ps
            wait_cause = "queue"
        elif name == "admit":
            if wait_start_ps is not None:
                comps_ps[_WAIT_COMPONENT[wait_cause]] += \
                    t_ps - wait_start_ps
                wait_start_ps = None
        elif name == "prefill":
            end = _end_ps(rec)
            comps_ps["prefill_s"] += end - t_ps
            last_fwd_end_ps, last_fwd_comp = end, "prefill_s"
            if first_token_ps is None:
                first_token_ps = end
        elif name == "spill_fetch":
            # KV-tier promotion (host-link or peer-DCN fetch): starts
            # exactly where the prefill interval ends, so it charges
            # its own component without overlapping prefill_s. It IS
            # forward work — the clip rule applies if a stall opens
            # mid-fetch — and it delays the first token when it backs
            # the first prefill.
            end = _end_ps(rec)
            comps_ps["spill_fetch_s"] += end - t_ps
            if first_token_ps == last_fwd_end_ps and \
                    last_fwd_end_ps == t_ps:
                first_token_ps = end
            last_fwd_end_ps, last_fwd_comp = end, "spill_fetch_s"
            counts["spill_fetches"] += 1
        elif name == "migrate":
            # failover KV migration (ISSUE 16): the transfer rides
            # INSIDE the failover wait window, so the open wait is
            # credited up to the migration start, the transfer gets
            # its own exact component, and the wait reopens at the
            # transfer's end (admission is gated on kv_ready_t, so
            # the re-admit stamp can never precede it)
            end = _end_ps(rec)
            if wait_start_ps is not None:
                comps_ps[_WAIT_COMPONENT[wait_cause]] += \
                    t_ps - wait_start_ps
            comps_ps["migration_stall_s"] += end - t_ps
            wait_start_ps = end
            wait_cause = "failover"
            counts["migrations"] += 1
        elif name in ("decode_step", "decode_step_dropped"):
            end = _end_ps(rec)
            comps_ps["decode_compute_s"] += end - t_ps
            last_fwd_end_ps, last_fwd_comp = end, "decode_compute_s"
            if name == "decode_step_dropped":
                counts["retries"] += 1
        elif name in ("evict", "table_corrupt", "engine_failed"):
            # a wait already open (a WAITING request on a dying
            # engine) is credited to its own cause first — the new
            # stall starts HERE, it does not swallow the queue time
            if wait_start_ps is not None:
                comps_ps[_WAIT_COMPONENT[wait_cause]] += \
                    t_ps - wait_start_ps
            # clip work the stall invalidated (e.g. a prefill whose
            # lane completion lay beyond the engine's death: its KV
            # died unborn, the adopter re-prefills from scratch)
            if last_fwd_end_ps is not None and last_fwd_end_ps > t_ps:
                comps_ps[last_fwd_comp] -= last_fwd_end_ps - t_ps
                if first_token_ps == last_fwd_end_ps:
                    # the first token died with its prefill; TTFT is
                    # whenever the re-prefill actually delivers one
                    first_token_ps = None
                last_fwd_end_ps = None
            wait_start_ps = t_ps
            if name == "evict":
                wait_cause = "evict"
                counts["evictions"] += 1
            elif name == "table_corrupt":
                # corruption recovery is requeue-for-re-prefill — same
                # mechanics (and component) as an eviction stall
                wait_cause = "evict"
                counts["corruptions"] += 1
            else:
                wait_cause = "failover"
        elif name == "adopt":
            counts["failovers"] += 1
            if wait_start_ps is None:
                wait_start_ps = t_ps
            wait_cause = "failover"
        elif name == "hot_swap":
            pause = float(rec.get("pause_s", 0.0) or 0.0)
            if pause:
                comps_ps["swap_stall_s"] += _ps(rec["t"] + pause) - t_ps
            counts["swaps"] += 1
        elif name == "shed":
            shed = True
            error = rec.get("reason")
        elif name == "finish":
            finish_ps = t_ps
            if "tokens" in rec:
                tokens = int(rec["tokens"])

    finished = finish_ps is not None and submit_ps is not None
    out: Dict[str, Any] = {"finished": finished, "shed": shed,
                           "error": error, "tokens": tokens,
                           "engines": sorted(engines), **counts}
    if not finished:
        out.update({"exact": False, "e2e_s": None})
        return out
    e2e_ps = finish_ps - submit_ps
    measured_ps = sum(comps_ps[c] for c in COMPONENTS[:-1])
    comps_ps["host_s"] = e2e_ps - measured_ps
    # the exactness invariant: ordered integer sum == e2e (true by
    # residual construction) AND nothing negative — a negative host or
    # component means intervals overlapped or leaked, i.e. the
    # bookkeeping, not the arithmetic, is wrong
    total_ps = sum(comps_ps[c] for c in COMPONENTS)
    out["exact"] = (total_ps == e2e_ps
                    and all(v >= 0 for v in comps_ps.values()))
    out["e2e_ps"] = e2e_ps
    out["e2e_s"] = e2e_ps / PS_PER_S
    for c in COMPONENTS:
        out[c[:-2] + "_ps"] = comps_ps[c]
        out[c] = comps_ps[c] / PS_PER_S
    if first_token_ps is not None:
        out["ttft_s"] = (first_token_ps - submit_ps) / PS_PER_S
        if tokens and tokens > 1:
            out["tpot_s"] = ((finish_ps - first_token_ps)
                             / (tokens - 1)) / PS_PER_S
    return out


def decompose(records: List[Dict[str, Any]]) -> Dict[Any, Dict[str, Any]]:
    """``load_trace_dir`` output -> per-trace-id decompositions."""
    return {tid: decompose_request(evs)
            for tid, evs in sorted(assemble(records).items(),
                                   key=lambda kv: str(kv[0]))}


__all__ = ["TracePlane", "enable", "disable", "active", "event",
           "serving_span", "flush", "load_trace_dir", "assemble",
           "decompose", "decompose_request", "COMPONENTS", "PS_PER_S",
           "TRACE_DIR_ENV", "TRACE_FLUSH_ENV"]


# auto-enable: same posture as the metrics plane — the launcher (or
# operator) sets PADDLE_TRACE_DIR for the gang; the PADDLE_TRAINER_ID
# guard keeps operator shells from masquerading as rank 0
if os.environ.get(TRACE_DIR_ENV) and os.environ.get("PADDLE_TRAINER_ID"):
    try:
        enable(os.environ[TRACE_DIR_ENV])
    except (OSError, ValueError):
        pass
