"""paddle.onnx — export surface (reference python/paddle/onnx/export.py,
which shells out to paddle2onnx).

Decision record (README "Deliberate omissions"): the portable artifact
of this framework is StableHLO, not ONNX — `paddle.jit.save` writes a
serialized StableHLO program + weights that any PJRT backend (TPU, GPU,
CPU) executes with versioned stability guarantees. `export` here keeps
the reference call sites working by producing that artifact and saying
so, instead of silently writing nothing.
"""

from __future__ import annotations

import warnings

__all__ = ["export"]


def export(layer, path: str, input_spec=None, opset_version=None,
           **configs):
    """Reference signature (onnx/export.py:30). Writes the StableHLO
    artifact via paddle.jit.save and returns its path; `opset_version`
    does not apply to StableHLO and is ignored with a warning."""
    from . import jit

    if opset_version is not None:
        warnings.warn(
            "paddle2_tpu.onnx.export writes a StableHLO artifact (the "
            "TPU-native portable format); opset_version is ignored. See "
            "README 'Deliberate omissions' for the rationale and the "
            "serving path.", UserWarning, stacklevel=2)
    jit.save(layer, path, input_spec=input_spec, **configs)
    return path
