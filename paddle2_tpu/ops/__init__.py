"""Op library assembly + Tensor method patching.

Mirrors the reference's split: tensor function namespaces
(python/paddle/tensor/{math,linalg,manipulation,creation,logic,search,random}.py)
plus the operator/method patch that the reference does in C++
(paddle/fluid/pybind/eager_math_op_patch.cc and eager_method.cc).
"""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from . import creation, extra, linalg, logic, manipulation, math, misc, random
from .dispatch import apply_op, ensure_tensor, rebind_inplace
from ..framework.tensor import Tensor

# re-export everything into paddle2_tpu.ops namespace
from .math import *          # noqa: F401,F403
from .creation import *      # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *        # noqa: F401,F403
from .logic import *         # noqa: F401,F403
from .random import *        # noqa: F401,F403
from .extra import *         # noqa: F401,F403
from .misc import *          # noqa: F401,F403


# ---------------------------------------------------------------------------
# Tensor indexing
# ---------------------------------------------------------------------------

def _convert_index(item):
    """Convert Tensors inside an index expression to jax arrays."""
    if isinstance(item, tuple):
        return tuple(_convert_index(i) for i in item)
    if isinstance(item, Tensor):
        return item._data
    if isinstance(item, (list, np.ndarray)):
        return jnp.asarray(item)
    return item


def _getitem(self, item):
    idx = _convert_index(item)
    # bool-mask indexing has data-dependent shape: resolve eagerly via numpy
    def has_bool(x):
        if isinstance(x, tuple):
            return builtins.any(has_bool(i) for i in x)
        return (hasattr(x, "dtype") and jnp.issubdtype(jnp.result_type(x), jnp.bool_)
                and getattr(x, "ndim", 0) > 0)
    if has_bool(idx) and not isinstance(self._data, jax.core.Tracer):
        np_idx = jax.tree_util.tree_map(np.asarray, idx) if isinstance(idx, tuple) \
            else np.asarray(idx)
        return Tensor(jnp.asarray(np.asarray(self._data)[np_idx]))
    return apply_op("getitem", lambda a: a[idx], (self,), {})


def _setitem(self, item, value):
    idx = _convert_index(item)
    if isinstance(value, Tensor):
        out = apply_op("setitem",
                       lambda a, v: a.at[idx].set(v.astype(a.dtype)),
                       (self, value), {})
    else:
        out = apply_op("setitem", lambda a: a.at[idx].set(value), (self,), {})
    return rebind_inplace(self, out)


# ---------------------------------------------------------------------------
# operator overloads
# ---------------------------------------------------------------------------

def _patch():
    T = Tensor
    T.__getitem__ = _getitem
    T.__setitem__ = _setitem

    T.__add__ = lambda s, o: math.add(s, o)
    T.__radd__ = lambda s, o: math.add(o, s)
    T.__sub__ = lambda s, o: math.subtract(s, o)
    T.__rsub__ = lambda s, o: math.subtract(o, s)
    T.__mul__ = lambda s, o: math.multiply(s, o)
    T.__rmul__ = lambda s, o: math.multiply(o, s)
    T.__truediv__ = lambda s, o: math.divide(s, o)
    T.__rtruediv__ = lambda s, o: math.divide(o, s)
    T.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    T.__rfloordiv__ = lambda s, o: math.floor_divide(o, s)
    T.__mod__ = lambda s, o: math.remainder(s, o)
    T.__pow__ = lambda s, o: math.pow(s, o)
    T.__rpow__ = lambda s, o: math.pow(o, s)
    T.__neg__ = lambda s: math.neg(s)
    T.__abs__ = lambda s: math.abs(s)
    T.__matmul__ = lambda s, o: linalg.matmul(s, o)
    T.__rmatmul__ = lambda s, o: linalg.matmul(o, s)
    T.__eq__ = lambda s, o: logic.equal(s, o)
    T.__ne__ = lambda s, o: logic.not_equal(s, o)
    T.__lt__ = lambda s, o: logic.less_than(s, o)
    T.__le__ = lambda s, o: logic.less_equal(s, o)
    T.__gt__ = lambda s, o: logic.greater_than(s, o)
    T.__ge__ = lambda s, o: logic.greater_equal(s, o)
    T.__and__ = lambda s, o: math.bitwise_and(s, o)
    T.__or__ = lambda s, o: math.bitwise_or(s, o)
    T.__xor__ = lambda s, o: math.bitwise_xor(s, o)
    T.__invert__ = lambda s: math.bitwise_not(s)

    # method forms — mirror paddle Tensor methods
    _method_sources = [math, creation, manipulation, linalg, logic,
                       random, extra]
    # misc holds non-tensor utilities too: attach ONLY tensor methods
    for _nm in ("rank", "is_complex", "is_integer", "is_floating_point",
                "reduce_as", "as_strided", "diagonal_scatter"):
        if not hasattr(T, _nm):
            setattr(T, _nm, getattr(misc, _nm))
    skip = {"to_tensor", "as_tensor", "pow"}
    for mod in _method_sources:
        for name in getattr(mod, "__all__", []):
            if name in skip or hasattr(T, name):
                continue
            fn = getattr(mod, name)
            if callable(fn):
                setattr(T, name, fn)
    # names that collide with @property or builtins get explicit treatment
    T.pow = lambda s, y, name=None: math.pow(s, y)
    T.add_ = lambda s, o: s.copy_(math.add(s, o))
    T.sub_ = lambda s, o: s.copy_(math.subtract(s, o))
    T.subtract_ = T.sub_
    T.multiply_ = lambda s, o: s.copy_(math.multiply(s, o))
    T.scale_ = lambda s, *a, **k: s.copy_(math.scale(s, *a, **k))
    T.clip_ = lambda s, *a, **k: s.copy_(math.clip(s, *a, **k))
    T.zero_ = lambda s: s.copy_(creation.zeros_like(s))
    T.fill_ = lambda s, v: s.copy_(creation.full_like(s, v))
    T.mean_all = lambda s: math.mean(s)
    T.dim = lambda s: s.ndim
    T.numel_ = T.numel if hasattr(T, "numel") else None


_patch()
del _patch


# ---------------------------------------------------------------------------
# generated in-place variants (reference `op_` surface): out-of-place op +
# rebind_inplace keeps the autograd edge (unlike raw copy_)
# ---------------------------------------------------------------------------

_INPLACE_BASES = [
    "addmm", "t", "cumsum", "cumprod", "logit", "equal", "cos",
    "tan", "logical_and", "less_than", "floor_divide", "remainder",
    "logical_or", "bitwise_and", "bitwise_or", "bitwise_xor",
    "bitwise_not", "less_equal", "triu", "sin", "tril", "pow", "acos",
    "expm1", "sinh", "sinc", "neg", "lgamma", "gammaincc", "gammainc",
    "square", "divide", "gammaln", "atan", "gcd", "lcm", "cast",
    # NOTE: "where" is excluded (its in-place target is x, not the
    # condition mask) — see the explicit where_ below
    "greater_equal", "erf", "greater_than", "tanh", "transpose",
    "flatten", "multiply", "log", "log2", "log10", "trunc", "frac",
    "digamma", "renorm", "multigammaln", "nan_to_num", "ldexp", "i0",
    "polygamma", "copysign", "bitwise_left_shift", "bitwise_right_shift",
    "masked_fill", "masked_scatter", "hypot", "abs", "exp", "sqrt",
    "rsqrt", "floor", "ceil", "round", "reciprocal", "logical_not",
    "unsqueeze", "squeeze", "reshape", "floor_mod", "cosh", "asin",
    "asinh", "acosh", "atanh",
]  # uniform/normal/exponential have hand-written in-place forms


def _gen_inplace():
    import sys
    mod = sys.modules[__name__]

    def make(base_fn, nm):
        def f(x, *args, **kwargs):
            x = ensure_tensor(x)
            return rebind_inplace(x, base_fn(x, *args, **kwargs))
        f.__name__ = nm
        f.__doc__ = f"In-place {base_fn.__name__} (reference {nm})."
        return f

    for base_name in _INPLACE_BASES:
        base = getattr(mod, base_name, None)
        if base is None or not callable(base):
            continue
        nm = base_name + "_"
        if hasattr(mod, nm):   # a hand-written in-place form wins
            continue
        fn = make(base, nm)
        setattr(mod, nm, fn)
        if not hasattr(Tensor, nm):
            setattr(Tensor, nm, fn)


_gen_inplace()

# aliases whose base has a different name
import sys as _sys
_mod = _sys.modules[__name__]
if hasattr(_mod, "remainder_"):
    mod_ = _mod.remainder_
    Tensor.mod_ = mod_


def bernoulli_(x, p=0.5, name=None):
    """In-place Bernoulli fill (reference bernoulli_)."""
    from ..framework import random as fr
    import jax as _jax
    x = ensure_tensor(x)
    u = _jax.random.uniform(fr.next_key(), tuple(x.shape))
    out = apply_op("bernoulli", lambda a: (u < p).astype(a.dtype), (x,),
                   {}, differentiable=False)
    return rebind_inplace(x, out)


def log_normal_(x, mean=1.0, std=2.0, name=None):
    """In-place log-normal fill (reference log_normal_)."""
    from ..framework import random as fr
    import jax as _jax
    import jax.numpy as _jnp
    x = ensure_tensor(x)
    eps = _jax.random.normal(fr.next_key(), tuple(x.shape))
    out = apply_op("log_normal",
                   lambda a: _jnp.exp(mean + std * eps).astype(a.dtype),
                   (x,), {}, differentiable=False)
    return rebind_inplace(x, out)


Tensor.bernoulli_ = bernoulli_
Tensor.log_normal_ = log_normal_


def where_(condition, x, y, name=None):
    """In-place where: writes the selected values into X (reference
    where_ contract — the condition is a read-only mask)."""
    x = ensure_tensor(x)
    out = manipulation.where(condition, x, y)
    return rebind_inplace(x, out)


Tensor.where_ = lambda self, condition, y, name=None: where_(condition,
                                                             self, y)
