"""Op library assembly + Tensor method patching.

Mirrors the reference's split: tensor function namespaces
(python/paddle/tensor/{math,linalg,manipulation,creation,logic,search,random}.py)
plus the operator/method patch that the reference does in C++
(paddle/fluid/pybind/eager_math_op_patch.cc and eager_method.cc).
"""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from . import creation, extra, linalg, logic, manipulation, math, random
from .dispatch import apply_op, ensure_tensor, rebind_inplace
from ..framework.tensor import Tensor

# re-export everything into paddle2_tpu.ops namespace
from .math import *          # noqa: F401,F403
from .creation import *      # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *        # noqa: F401,F403
from .logic import *         # noqa: F401,F403
from .random import *        # noqa: F401,F403
from .extra import *         # noqa: F401,F403


# ---------------------------------------------------------------------------
# Tensor indexing
# ---------------------------------------------------------------------------

def _convert_index(item):
    """Convert Tensors inside an index expression to jax arrays."""
    if isinstance(item, tuple):
        return tuple(_convert_index(i) for i in item)
    if isinstance(item, Tensor):
        return item._data
    if isinstance(item, (list, np.ndarray)):
        return jnp.asarray(item)
    return item


def _getitem(self, item):
    idx = _convert_index(item)
    # bool-mask indexing has data-dependent shape: resolve eagerly via numpy
    def has_bool(x):
        if isinstance(x, tuple):
            return builtins.any(has_bool(i) for i in x)
        return (hasattr(x, "dtype") and jnp.issubdtype(jnp.result_type(x), jnp.bool_)
                and getattr(x, "ndim", 0) > 0)
    if has_bool(idx) and not isinstance(self._data, jax.core.Tracer):
        np_idx = jax.tree_util.tree_map(np.asarray, idx) if isinstance(idx, tuple) \
            else np.asarray(idx)
        return Tensor(jnp.asarray(np.asarray(self._data)[np_idx]))
    return apply_op("getitem", lambda a: a[idx], (self,), {})


def _setitem(self, item, value):
    idx = _convert_index(item)
    if isinstance(value, Tensor):
        out = apply_op("setitem",
                       lambda a, v: a.at[idx].set(v.astype(a.dtype)),
                       (self, value), {})
    else:
        out = apply_op("setitem", lambda a: a.at[idx].set(value), (self,), {})
    return rebind_inplace(self, out)


# ---------------------------------------------------------------------------
# operator overloads
# ---------------------------------------------------------------------------

def _patch():
    T = Tensor
    T.__getitem__ = _getitem
    T.__setitem__ = _setitem

    T.__add__ = lambda s, o: math.add(s, o)
    T.__radd__ = lambda s, o: math.add(o, s)
    T.__sub__ = lambda s, o: math.subtract(s, o)
    T.__rsub__ = lambda s, o: math.subtract(o, s)
    T.__mul__ = lambda s, o: math.multiply(s, o)
    T.__rmul__ = lambda s, o: math.multiply(o, s)
    T.__truediv__ = lambda s, o: math.divide(s, o)
    T.__rtruediv__ = lambda s, o: math.divide(o, s)
    T.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    T.__rfloordiv__ = lambda s, o: math.floor_divide(o, s)
    T.__mod__ = lambda s, o: math.remainder(s, o)
    T.__pow__ = lambda s, o: math.pow(s, o)
    T.__rpow__ = lambda s, o: math.pow(o, s)
    T.__neg__ = lambda s: math.neg(s)
    T.__abs__ = lambda s: math.abs(s)
    T.__matmul__ = lambda s, o: linalg.matmul(s, o)
    T.__rmatmul__ = lambda s, o: linalg.matmul(o, s)
    T.__eq__ = lambda s, o: logic.equal(s, o)
    T.__ne__ = lambda s, o: logic.not_equal(s, o)
    T.__lt__ = lambda s, o: logic.less_than(s, o)
    T.__le__ = lambda s, o: logic.less_equal(s, o)
    T.__gt__ = lambda s, o: logic.greater_than(s, o)
    T.__ge__ = lambda s, o: logic.greater_equal(s, o)
    T.__and__ = lambda s, o: math.bitwise_and(s, o)
    T.__or__ = lambda s, o: math.bitwise_or(s, o)
    T.__xor__ = lambda s, o: math.bitwise_xor(s, o)
    T.__invert__ = lambda s: math.bitwise_not(s)

    # method forms — mirror paddle Tensor methods
    _method_sources = [math, creation, manipulation, linalg, logic,
                       random, extra]
    skip = {"to_tensor", "as_tensor", "pow"}
    for mod in _method_sources:
        for name in getattr(mod, "__all__", []):
            if name in skip or hasattr(T, name):
                continue
            fn = getattr(mod, name)
            if callable(fn):
                setattr(T, name, fn)
    # names that collide with @property or builtins get explicit treatment
    T.pow = lambda s, y, name=None: math.pow(s, y)
    T.add_ = lambda s, o: s.copy_(math.add(s, o))
    T.sub_ = lambda s, o: s.copy_(math.subtract(s, o))
    T.subtract_ = T.sub_
    T.multiply_ = lambda s, o: s.copy_(math.multiply(s, o))
    T.scale_ = lambda s, *a, **k: s.copy_(math.scale(s, *a, **k))
    T.clip_ = lambda s, *a, **k: s.copy_(math.clip(s, *a, **k))
    T.zero_ = lambda s: s.copy_(creation.zeros_like(s))
    T.fill_ = lambda s, v: s.copy_(creation.full_like(s, v))
    T.mean_all = lambda s: math.mean(s)
    T.dim = lambda s: s.ndim
    T.numel_ = T.numel if hasattr(T, "numel") else None


_patch()
del _patch
