"""Tensor creation ops (python/paddle/tensor/creation.py parity)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .dispatch import apply_op, ensure_tensor
from ..framework import core
from ..framework.tensor import Tensor, to_tensor  # re-export to_tensor

__all__ = ["to_tensor", "zeros", "ones", "full", "empty", "zeros_like",
           "ones_like", "full_like", "empty_like", "arange", "linspace",
           "logspace", "eye", "meshgrid", "diag", "diagflat", "diag_embed",
           "tril", "triu", "tril_indices", "triu_indices", "assign", "clone",
           "complex", "polar", "as_tensor"]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().reshape(-1))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) if not isinstance(s, Tensor) else int(s.item())
                 for s in shape)


def _dt(dtype, default=None):
    d = core.convert_dtype(dtype)
    return d if d is not None else (default or core.get_default_dtype())


def zeros(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None) -> Tensor:
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = (core.bool_ if isinstance(fill_value, bool)
                 else core.int64 if isinstance(fill_value, int)
                 else core.get_default_dtype())
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None) -> Tensor:
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jnp.zeros_like(x._data, dtype=core.convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jnp.ones_like(x._data, dtype=core.convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jnp.full_like(x._data, fill_value,
                                dtype=core.convert_dtype(dtype)))


def empty_like(x, dtype=None, name=None) -> Tensor:
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None) -> Tensor:
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (core.int64 if all(isinstance(v, int) for v in (start, end, step))
                 else core.get_default_dtype())
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None) -> Tensor:
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None) -> Tensor:
    def _v(x):
        return x.item() if isinstance(x, Tensor) else x
    return Tensor(jnp.logspace(_v(start), _v(stop), int(_v(num)), base=_v(base),
                               dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None) -> Tensor:
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns is not None else None,
                          dtype=_dt(dtype)))


def meshgrid(*args, **kwargs) -> List[Tensor]:
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    ts = [ensure_tensor(a) for a in args]
    outs = apply_op("meshgrid", lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")),
                    tuple(ts), {})
    return list(outs)


def diag(x, offset=0, padding_value=0, name=None) -> Tensor:
    x = ensure_tensor(x)
    def fn(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a), k=offset)
                out = out + (1 - mask) * padding_value
            return out.astype(a.dtype)
        return jnp.diag(a, k=offset)
    return apply_op("diag", fn, (x,), {})


def diagflat(x, offset=0, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("diagflat", lambda a: jnp.diagflat(a, k=offset), (x,), {})


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None) -> Tensor:
    input = ensure_tensor(input)
    def fn(a):
        n = a.shape[-1] + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(a)
        # move the two new axes to dim1/dim2
        nd = out.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        if (d1, d2) != (nd - 2, nd - 1):
            perm = [i for i in range(nd - 2)]
            order = list(range(nd - 2))
            # insert axes
            axes = sorted([(d1, nd - 2), (d2, nd - 1)])
            for pos, src in axes:
                order.insert(pos, src)
            out = jnp.transpose(out, order)
        return out
    return apply_op("diag_embed", fn, (input,), {})


def tril(x, diagonal=0, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("tril", lambda a: jnp.tril(a, k=diagonal), (x,), {})


def triu(x, diagonal=0, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("triu", lambda a: jnp.triu(a, k=diagonal), (x,), {})


def tril_indices(row, col=None, offset=0, dtype="int64", name=None) -> Tensor:
    col = col if col is not None else row
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=core.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None) -> Tensor:
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=core.convert_dtype(dtype)))


def assign(x, output: Optional[Tensor] = None) -> Tensor:
    x = ensure_tensor(x)
    out = apply_op("assign", lambda a: a + 0, (x,), {})
    if output is not None:
        output._replace_data(out._data)
        return output
    return out


def clone(x, name=None) -> Tensor:
    return ensure_tensor(x).clone()


def complex(real, imag, name=None) -> Tensor:
    real, imag = ensure_tensor(real), ensure_tensor(imag)
    return apply_op("complex", jax.lax.complex, (real, imag), {})


def polar(abs, angle, name=None) -> Tensor:
    abs, angle = ensure_tensor(abs), ensure_tensor(angle)
    return apply_op("polar",
                    lambda r, t: jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t)),
                    (abs, angle), {})


def as_tensor(data, dtype=None, place=None) -> Tensor:
    return data if isinstance(data, Tensor) and dtype is None else to_tensor(
        data, dtype=dtype, place=place)
