"""Eager op dispatch: run a pure JAX function over Tensors, recording the tape.

Plays the role of the reference's generated ``*_ad_func`` chain
(``eager_gen.py`` output: AMP cast → create GradNode → phi kernel call,
SURVEY.md §3.1). Here the "kernel" is a pure JAX function (XLA-compiled and
cached by shape under the hood) and the GradNode's backward is the JAX VJP of
that same function — one definition serves forward, backward, and the
jit.to_static trace path.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..framework import core
from ..framework.tensor import Tensor
from ..autograd.tape import GradNode

_OP_REGISTRY: Dict[str, Callable] = {}

# optional build-then-run recorder (paddle.static Program capture): when
# set, every dispatched op reports (name, fn, kwargs, inputs, outputs) —
# one attribute check on the hot path, None in normal eager execution
_STATIC_RECORDER = None


def set_static_recorder(cb) -> None:
    global _STATIC_RECORDER
    _STATIC_RECORDER = cb


def _replay_fn(name: str, fn: Callable, kwargs: Dict[str, Any]):
    """The callable a static Program replays for this op: kwargs bound,
    and the autocast decision BAKED at record time — build-time
    execution ran through cast_inputs_for_op under the thread's amp
    state, which will not exist at replay, so the resolved target dtype
    is frozen into the node (or an auto_cast-built program would
    silently replay fp32)."""
    st = getattr(core._tls(), "amp_state", None)
    target = None
    if st is not None and getattr(st, "enable", False):
        from ..amp import amp_lists
        white = (name in amp_lists.WHITE_LIST
                 or name in st.custom_white) \
            and name not in st.custom_black
        black = (name in amp_lists.BLACK_LIST
                 or name in st.custom_black) \
            and name not in st.custom_white
        if st.level == "O2":
            target = jnp.float32 if black else st.dtype
        elif white:
            target = st.dtype
        elif black:
            target = jnp.float32
    if target is None and not kwargs:
        return fn

    def replay(*xs):
        if target is not None:
            xs = tuple(a.astype(target)
                       if jnp.issubdtype(a.dtype, jnp.floating)
                       and a.dtype != target else a for a in xs)
        return fn(*xs, **kwargs) if kwargs else fn(*xs)
    return replay


def _maybe_check_finite(name, out):
    """FLAGS_check_nan_inf forward pass (reference nan_inf_utils_detail:
    per-op output scan). Debug-only: forces a host sync per op."""
    from ..flags import flag_value
    if not flag_value("check_nan_inf"):
        return
    outs = out if isinstance(out, (tuple, list)) else (out,)
    for i, a in enumerate(outs):
        if (hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.inexact)
                and not isinstance(a, jax.core.Tracer)):
            if bool(jnp.any(~jnp.isfinite(a))):
                raise FloatingPointError(
                    f"nan/inf in FORWARD output {i} of op '{name}' "
                    f"(FLAGS_check_nan_inf is enabled)")


def _harmonize_placements(tensors) -> tuple:
    """When any operand lives on a multi-device mesh, promote
    single-device-committed payloads to mesh-replicated so eager ops can
    mix them (XLA refuses computations whose committed device sets
    differ). The mesh comes from the multi-device operand itself (a
    shard_tensor'd DistTensor carries its ProcessMesh), falling back to
    the installed global mesh. The promoted placement is written BACK
    onto the owning Tensor so the device_put is paid once per tensor,
    not once per op."""
    import sys
    from jax.sharding import NamedSharding, PartitionSpec
    arrays = tuple(t._data for t in tensors)
    mesh = None
    for a in arrays:
        s = getattr(a, "sharding", None)
        if (isinstance(s, NamedSharding) and len(s.device_set) > 1):
            if mesh is None:
                mesh = s.mesh
            elif s.mesh != mesh:
                raise ValueError(
                    "operands are committed to DIFFERENT meshes "
                    f"({mesh.axis_names}{mesh.devices.shape} vs "
                    f"{s.mesh.axis_names}{s.mesh.devices.shape}); "
                    "reshard one side explicitly (dist.reshard) — eager "
                    "ops will not silently re-place across meshes (the "
                    "multi-mesh pipeline dataloader routes inputs and "
                    "labels to different stage meshes on purpose)")
    if mesh is None:
        mesh_mod = sys.modules.get("paddle2_tpu.distributed.mesh")
        if mesh_mod is None or not mesh_mod.mesh_initialized():
            return arrays
        if any(getattr(a, "sharding", None) is not None
               and len(a.sharding.device_set) > 1 for a in arrays):
            mesh = mesh_mod.get_mesh()
        else:
            return arrays
    repl = NamedSharding(mesh, PartitionSpec())
    out = []
    for t, a in zip(tensors, arrays):
        s = getattr(a, "sharding", None)
        if s is not None and len(s.device_set) == 1 \
                and not isinstance(a, jax.core.Tracer):
            a = jax.device_put(a, repl)
            t._data = a
        out.append(a)
    return tuple(out)


def register_op(name: str, fn: Callable) -> None:
    _OP_REGISTRY[name] = fn


def get_op(name: str) -> Callable:
    return _OP_REGISTRY[name]


def _wrap_outputs(name, out, requires_grad, node_builder):
    """Wrap raw jax output(s) into Tensor(s), attaching the grad node."""
    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]
    node = node_builder(outs) if requires_grad else None
    tensors = []
    for i, o in enumerate(outs):
        t = Tensor(o, stop_gradient=not requires_grad)
        if node is not None:
            t._grad_node = node
            t._output_index = i
        tensors.append(t)
    if multi:
        return type(out)(tensors) if isinstance(out, tuple) else tensors
    return tensors[0]


def _is_float(x) -> bool:
    return jnp.issubdtype(jnp.result_type(x), jnp.inexact)


def apply_op(name: str, fn: Callable, tensors: Sequence[Tensor],
             kwargs: Dict[str, Any], differentiable: bool = True):
    """Execute `fn(*arrays, **kwargs)` over the payloads of `tensors`.

    When the tape is active and any float input requires grad, linearize with
    jax.vjp and record a GradNode; otherwise run the function directly (XLA's
    jit-by-default primitive cache makes this the cheap path).
    """
    arrays = _harmonize_placements(tensors)
    if getattr(core._tls(), "amp_state", None) is not None:
        from ..amp import cast_inputs_for_op
        arrays = cast_inputs_for_op(name, arrays)
    needs_grad = (differentiable
                  and core.is_grad_enabled()
                  and any(not t.stop_gradient and _is_float(t._data)
                          for t in tensors))
    if not needs_grad:
        out = fn(*arrays, **kwargs) if kwargs else fn(*arrays)
        _maybe_check_finite(name, out)
        res = _wrap_outputs(name, out, False, None)
        if _STATIC_RECORDER is not None:
            _STATIC_RECORDER(name, _replay_fn(name, fn, kwargs), {},
                             tensors, res)
        return res

    closed = (lambda *xs: fn(*xs, **kwargs)) if kwargs else fn
    out, vjp_fn = jax.vjp(closed, *arrays)
    _maybe_check_finite(name, out)

    def node_builder(outs):
        inputs = list(tensors)
        out_arrays = out if isinstance(out, (tuple, list)) else (out,)
        out_shardings = [getattr(o, "sharding", None) for o in out_arrays]

        def run_vjp(cts):
            # a downstream op may have promoted activations onto the mesh
            # AFTER this node recorded its residuals: reshard cotangents
            # back to the forward output's placement so vjp_fn's captured
            # residuals and the cotangent share one device set
            def fix(c, s):
                if (s is not None and c is not None
                        and not isinstance(c, jax.core.Tracer)
                        and getattr(c, "sharding", None) is not None
                        and c.sharding.device_set != s.device_set):
                    return jax.device_put(c, s)
                return c

            if isinstance(cts, (tuple, list)):
                cts = tuple(fix(c, s) for c, s in zip(cts, out_shardings))
            else:
                cts = fix(cts, out_shardings[0])
            raw = vjp_fn(cts)
            # jax returns float0 for non-differentiable (integer) inputs;
            # normalize those to None so the tape skips them.
            return tuple(
                None if (g is None or g.dtype == jax.dtypes.float0) else g
                for g in raw)

        avals = [(tuple(o.shape), o.dtype) for o in outs]
        return GradNode(name, run_vjp, inputs, avals,
                        out_is_tuple=isinstance(out, (tuple, list)),
                        fwd_fn=closed)

    res = _wrap_outputs(name, out, True, node_builder)
    if _STATIC_RECORDER is not None:
        _STATIC_RECORDER(name, _replay_fn(name, fn, kwargs), {},
                         tensors, res)
    return res


class _ShadowTensor(Tensor):
    """Pre-inplace-write identity of a tensor: keeps the old grad edge alive
    while routing leaf accumulation back to the original tensor's .grad."""

    __slots__ = ("_origin",)

    def _accumulate_grad(self, g):
        self._origin._accumulate_grad(g)


def rebind_inplace(x: Tensor, out: Tensor) -> Tensor:
    """Finish an in-place op: `out = f(x, ...)` replaces x's payload/history.

    The grad node recorded for `out` holds `x` among its inputs; left as-is
    that becomes a self-edge once x adopts the new node (deadlocking the
    backward topo-sort). Swap in a shadow carrying x's OLD autograd identity.
    """
    node = out._grad_node
    if node is not None:
        shadow = _ShadowTensor.__new__(_ShadowTensor)
        shadow._data = x._data
        shadow.stop_gradient = x.stop_gradient
        shadow.grad = None
        shadow._grad_node = x._grad_node
        shadow._output_index = x._output_index
        shadow.name = x.name
        shadow.persistable = False
        shadow.trainable = x.trainable
        shadow._hooks = x._hooks
        shadow._origin = x
        node.inputs = [shadow if t is x else t for t in node.inputs]
    x._replace_data(out._data)
    x._grad_node = out._grad_node
    x._output_index = out._output_index
    return x


def ensure_tensor(x, ref: Tensor = None) -> Tensor:
    """Coerce python scalars / numpy arrays to Tensor (binary-op promotion)."""
    if isinstance(x, Tensor):
        return x
    dtype = None
    if ref is not None and isinstance(x, (int, float)) and not isinstance(x, bool):
        ref_is_float = jnp.issubdtype(ref.dtype, jnp.inexact)
        if isinstance(x, int) or ref_is_float:
            dtype = ref.dtype  # follow the tensor's dtype
        # float scalar with integer tensor: leave dtype None so the result
        # promotes to float (paddle promotes, never truncates the scalar)
    return Tensor(core.to_jax_array(x, dtype))
