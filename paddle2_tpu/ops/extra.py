"""Long-tail tensor functions (reference python/paddle/tensor/{math,
manipulation,linalg,search,stat}.py surface widening — the ops the core
modules don't cover)."""

from __future__ import annotations

import builtins
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from .dispatch import apply_op, ensure_tensor, rebind_inplace

__all__ = [
    "histogramdd", "trapezoid", "cumulative_trapezoid", "nanmedian",
    "nanquantile", "take", "diagonal", "real", "imag",
    "bitwise_left_shift", "bitwise_right_shift", "frexp", "polygamma",
    "multigammaln", "gammaln", "gammainc", "gammaincc", "vander",
    "cartesian_prod", "combinations", "column_stack", "row_stack",
    "hstack", "vstack", "dstack", "tensor_split", "hsplit", "vsplit",
    "dsplit", "block_diag", "unflatten", "positive", "negative",
    "signbit", "isneginf", "isposinf", "isreal", "aminmax",
    "float_power", "addcdiv", "addcmul", "baddbmm", "cdist", "pdist",
    "flipud", "fliplr", "logaddexp2", "sinc", "xlogy", "exp2",
    "clip_by_norm", "sgn", "fix", "fmod", "isin", "vecdot", "vdot",
    "slice_scatter", "select_scatter", "top_p_sampling",
]


def _u(name, fn, *ts, **kw):
    return apply_op(name, fn, tuple(ensure_tensor(t) for t in ts), kw)


# ------------------------------------------------------------- elementwise

def positive(x, name=None):
    return _u("positive", lambda a: +a, x)


def negative(x, name=None):
    return _u("negative", jnp.negative, x)


def signbit(x, name=None):
    return _u("signbit", jnp.signbit, x)


def isneginf(x, name=None):
    return _u("isneginf", jnp.isneginf, x)


def isposinf(x, name=None):
    return _u("isposinf", jnp.isposinf, x)


def isreal(x, name=None):
    return _u("isreal", jnp.isreal, x)


def float_power(x, y, name=None):
    return _u("float_power", lambda a, b: jnp.float_power(a, b), x, y)


def logaddexp2(x, y, name=None):
    return _u("logaddexp2", jnp.logaddexp2, x, y)


def sinc(x, name=None):
    return _u("sinc", jnp.sinc, x)


def xlogy(x, y, name=None):
    from jax.scipy.special import xlogy as _xlogy
    return _u("xlogy", _xlogy, x, y)


def exp2(x, name=None):
    return _u("exp2", jnp.exp2, x)


def sgn(x, name=None):
    """sign for real; unit phasor for complex (tensor/math.py sgn)."""
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.maximum(mag, 1e-30))
        return jnp.sign(a)
    return _u("sgn", f, x)


def fix(x, name=None):
    return _u("fix", jnp.trunc, x)  # jnp.fix removed in JAX 0.10; trunc is identical


def fmod(x, y, name=None):
    return _u("fmod", jnp.fmod, x, y)


def frexp(x, name=None):
    x = ensure_tensor(x)
    return apply_op("frexp", jnp.frexp, (x,), {})


def polygamma(x, n, name=None):
    from jax.scipy.special import polygamma as _pg
    return _u("polygamma", lambda a: _pg(int(n), a), x)


def gammaln(x, name=None):
    from jax.scipy.special import gammaln as _g
    return _u("gammaln", _g, x)


def multigammaln(x, p, name=None):
    from jax.scipy.special import multigammaln as _mg
    return _u("multigammaln", lambda a: _mg(a, int(p)), x)


def gammainc(x, y, name=None):
    from jax.scipy.special import gammainc as _gi
    return _u("gammainc", _gi, x, y)


def gammaincc(x, y, name=None):
    from jax.scipy.special import gammaincc as _gic
    return _u("gammaincc", _gic, x, y)


def bitwise_left_shift(x, y, is_arithmetic=True, name=None):
    # left shifts are identical arithmetic vs logical
    return _u("bitwise_left_shift", jnp.left_shift, x, y)


def bitwise_right_shift(x, y, is_arithmetic=True, name=None):
    if is_arithmetic:
        return _u("bitwise_right_shift", jnp.right_shift, x, y)

    def f(a, b):  # logical shift: reinterpret as unsigned, shift, back
        if jnp.issubdtype(a.dtype, jnp.signedinteger):
            u = {jnp.int8: jnp.uint8, jnp.int16: jnp.uint16,
                 jnp.int32: jnp.uint32, jnp.int64: jnp.uint64}[
                jnp.dtype(a.dtype).type]
            return jax.lax.bitcast_convert_type(
                jnp.right_shift(jax.lax.bitcast_convert_type(a, u),
                                b.astype(u)), a.dtype)
        return jnp.right_shift(a, b)
    return _u("bitwise_right_shift_logical", f, x, y)


def addcdiv(input, tensor1, tensor2, value=1.0, name=None):
    return _u("addcdiv", lambda a, b, c: a + value * b / c, input, tensor1,
              tensor2)


def addcmul(input, tensor1, tensor2, value=1.0, name=None):
    return _u("addcmul", lambda a, b, c: a + value * b * c, input, tensor1,
              tensor2)


# ------------------------------------------------------------ reductions

def nanmedian(x, axis=None, keepdim=False, name=None):
    return _u("nanmedian",
              lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim), x)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return _u("nanquantile",
              lambda a: jnp.nanquantile(a, q, axis=axis, keepdims=keepdim),
              x)


def aminmax(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply_op("aminmax",
                    lambda a: (jnp.min(a, axis=axis, keepdims=keepdim),
                               jnp.max(a, axis=axis, keepdims=keepdim)),
                    (x,), {})


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = ensure_tensor(y)
    if x is not None:
        return apply_op("trapezoid",
                        lambda a, b: jnp.trapezoid(a, b, axis=axis),
                        (y, ensure_tensor(x)), {})
    return apply_op("trapezoid",
                    lambda a: jnp.trapezoid(a, dx=dx or 1.0, axis=axis),
                    (y,), {})


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = ensure_tensor(y)

    def f(a, *rest):
        b = rest[0] if rest else None
        sl1 = [slice(None)] * a.ndim
        sl2 = [slice(None)] * a.ndim
        sl1[axis] = slice(1, None)
        sl2[axis] = slice(None, -1)
        avg = (a[tuple(sl1)] + a[tuple(sl2)]) / 2.0
        if b is not None:
            d = b[tuple(sl1)] - b[tuple(sl2)]
        else:
            d = dx or 1.0
        return jnp.cumsum(avg * d, axis=axis)
    ts = (y,) if x is None else (y, ensure_tensor(x))
    return apply_op("cumulative_trapezoid", f, ts, {})


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    x_np = np.asarray(ensure_tensor(x).numpy())
    w_np = np.asarray(ensure_tensor(weights).numpy()) \
        if weights is not None else None
    hist, edges = np.histogramdd(x_np, bins=bins, range=ranges,
                                 density=density, weights=w_np)
    return (Tensor(jnp.asarray(hist)),
            [Tensor(jnp.asarray(e)) for e in edges])


# --------------------------------------------------------- index / select

def take(x, index, mode="raise", name=None):
    xt, it = ensure_tensor(x), ensure_tensor(index)
    if mode == "raise" and not isinstance(it._data, jax.core.Tracer):
        n = int(np.prod(xt.shape))
        idx_np = np.asarray(it.numpy())
        if idx_np.size and (idx_np.min() < -n or idx_np.max() >= n):
            raise IndexError(
                f"take(mode='raise'): index out of range for tensor with "
                f"{n} elements (got [{idx_np.min()}, {idx_np.max()}])")

    def f(a, i):
        flat = a.reshape(-1)
        if mode == "wrap":
            i = i % flat.shape[0]
        elif mode == "clip":
            i = jnp.clip(i, 0, flat.shape[0] - 1)
        return flat[i]
    return apply_op("take", f, (xt, it), {})


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return _u("diagonal",
              lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                     axis2=axis2), x)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return _u("isin",
              lambda a, b: jnp.isin(a, b, invert=invert), x, test_x)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def f(a, v):
        idx = [slice(None)] * a.ndim
        for ax, st, en, sr in zip(axes, starts, ends, strides):
            idx[ax] = slice(st, en, sr)
        return a.at[tuple(idx)].set(v.astype(a.dtype))
    return _u("slice_scatter", f, x, value)


def select_scatter(x, values, axis, index, name=None):
    def f(a, v):
        idx = [slice(None)] * a.ndim
        idx[axis] = index
        return a.at[tuple(idx)].set(v.astype(a.dtype))
    return _u("select_scatter", f, x, values)


# ----------------------------------------------------------- composition

def vander(x, n=None, increasing=False, name=None):
    return _u("vander",
              lambda a: jnp.vander(a, N=n, increasing=increasing), x)


def cartesian_prod(x, name=None):
    ts = [ensure_tensor(t) for t in (x if isinstance(x, (list, tuple))
                                     else [x])]

    def f(*arrays):
        grids = jnp.meshgrid(*arrays, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    return apply_op("cartesian_prod", f, tuple(ts), {})


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools
    x = ensure_tensor(x)
    n = int(x.shape[0])
    gen = (itertools.combinations_with_replacement if with_replacement
           else itertools.combinations)
    idx = np.asarray(list(gen(range(n), r)), np.int32).reshape(-1, r)

    def f(a):
        return a[jnp.asarray(idx)]
    return apply_op("combinations", f, (x,), {})


def _stack_list(name, fn, xs):
    ts = tuple(ensure_tensor(t) for t in xs)
    return apply_op(name, lambda *a: fn(a), ts, {})


def column_stack(x, name=None):
    return _stack_list("column_stack", jnp.column_stack, x)


def row_stack(x, name=None):
    return _stack_list("row_stack", jnp.vstack, x)


def hstack(x, name=None):
    return _stack_list("hstack", jnp.hstack, x)


def vstack(x, name=None):
    return _stack_list("vstack", jnp.vstack, x)


def dstack(x, name=None):
    return _stack_list("dstack", jnp.dstack, x)


def _split_list(name, fn, x, arg, axis=None):
    x = ensure_tensor(x)
    kw = {} if axis is None else {"axis": axis}

    def f(a):
        return tuple(fn(a, arg, **kw))
    return list(apply_op(name, f, (x,), {}))


def tensor_split(x, num_or_indices, axis=0, name=None):
    return _split_list("tensor_split", jnp.array_split, x, num_or_indices,
                       axis)


def hsplit(x, num_or_indices, name=None):
    return _split_list("hsplit", jnp.hsplit, x, num_or_indices)


def vsplit(x, num_or_indices, name=None):
    return _split_list("vsplit", jnp.vsplit, x, num_or_indices)


def dsplit(x, num_or_indices, name=None):
    return _split_list("dsplit", jnp.dsplit, x, num_or_indices)


def block_diag(inputs, name=None):
    from jax.scipy.linalg import block_diag as _bd
    return _stack_list("block_diag", lambda a: _bd(*a), inputs)


def unflatten(x, axis, shape, name=None):
    def f(a):
        ax = axis % a.ndim  # normalize negative axes
        s = list(a.shape)
        new = list(shape)
        if -1 in new:
            known = int(np.prod([d for d in new if d != -1]))
            new[new.index(-1)] = s[ax] // known
        return a.reshape(s[:ax] + new + s[ax + 1:])
    return _u("unflatten", f, x)


def flipud(x, name=None):
    return _u("flipud", jnp.flipud, x)


def fliplr(x, name=None):
    return _u("fliplr", jnp.fliplr, x)


# ----------------------------------------------------------------- linalg

def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    from .linalg import _mxu_precision
    return _u("baddbmm",
              lambda i, a, b: beta * i + alpha * jnp.matmul(
                  a, b, precision=_mxu_precision(a, b)), input, x, y)


def vecdot(x, y, axis=-1, name=None):
    return _u("vecdot",
              lambda a, b: jnp.sum(jnp.conj(a) * b, axis=axis), x, y)


def vdot(x, y, name=None):
    return _u("vdot", lambda a, b: jnp.vdot(a, b), x, y)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    def f(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.maximum(jnp.sum(d * d, -1), 1e-30))
        return jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)
    return _u("cdist", f, x, y)


def pdist(x, p=2.0, name=None):
    x = ensure_tensor(x)
    n = int(x.shape[0])
    iu = np.triu_indices(n, k=1)

    def f(a):
        d = a[:, None, :] - a[None, :, :]
        if p == 2.0:
            m = jnp.sqrt(jnp.maximum(jnp.sum(d * d, -1), 1e-30))
        else:
            m = jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)
        return m[iu]
    return apply_op("pdist", f, (x,), {})


def clip_by_norm(x, max_norm, name=None):
    def f(a):
        norm = jnp.sqrt(jnp.maximum(jnp.sum(a * a), 1e-30))
        return jnp.where(norm > max_norm, a * (max_norm / norm), a)
    return _u("clip_by_norm", f, x)


# ----------------------------------------------------------------- complex

def real(x, name=None):
    return _u("real", jnp.real, x)


def imag(x, name=None):
    return _u("imag", jnp.imag, x)


# ----------------------------------------------------------------- search

def _nucleus_keep_mask(sorted_probs, p):
    """Keep-mask over DESC-sorted probs: smallest prefix reaching mass p
    (the single source of the nucleus boundary rule)."""
    cum = jnp.cumsum(sorted_probs, -1)
    return cum - sorted_probs < p[..., None]


def nucleus_filter_logits(logits, p):
    """Mask logits outside the top-p nucleus to -inf (per row)."""
    probs = jax.nn.softmax(logits, axis=-1)
    order = jnp.argsort(-probs, axis=-1)
    sp = jnp.take_along_axis(probs, order, -1)
    keep_sorted = _nucleus_keep_mask(sp, p)
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(logits.shape[0])[:, None], order].set(keep_sorted)
    return jnp.where(keep, logits, -jnp.inf)


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling over the last axis (tensor/search.py
    top_p_sampling): keeps the smallest prefix of sorted probs whose mass
    reaches ps, renormalizes, samples one index per row."""
    from ..framework import random as fr
    x = ensure_tensor(x)
    ps_t = ensure_tensor(ps)
    key = (jax.random.PRNGKey(int(seed)) if seed not in (None, -1)
           else fr.next_key())

    def f(probs, p):
        order = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, order, -1)
        keep = _nucleus_keep_mask(sorted_p, p)
        filt = jnp.where(keep, sorted_p, 0.0)
        filt = filt / jnp.maximum(filt.sum(-1, keepdims=True), 1e-30)
        idx_sorted = jax.random.categorical(key, jnp.log(
            jnp.maximum(filt, 1e-30)))
        picked = jnp.take_along_axis(order, idx_sorted[..., None], -1)
        return picked
    ids = apply_op("top_p_sampling", f, (x, ps_t), {},
                   differentiable=False)
    return ids, None
