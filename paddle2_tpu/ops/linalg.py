"""Linear algebra ops (python/paddle/tensor/linalg.py:191 matmul etc.).

matmul is THE MXU op: keep operands batched and let XLA tile onto the
systolic array. All decompositions ride jax.numpy.linalg (lowered to
XLA custom calls / QR-based routines on TPU).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .dispatch import apply_op, ensure_tensor
from ..framework import core
from ..framework.tensor import Tensor

__all__ = ["matmul", "bmm", "mm", "mv", "dot", "norm", "dist", "cond",
           "cholesky", "cholesky_solve", "qr", "svd", "pca_lowrank", "inv",
           "pinv", "det", "slogdet", "solve", "triangular_solve", "lstsq",
           "eig", "eigh", "eigvals", "eigvalsh", "matrix_power", "matrix_rank",
           "multi_dot", "corrcoef", "cov", "householder_product", "lu",
           "lu_unpack", "einsum", "vector_norm", "matrix_norm",
           "cholesky_inverse", "matrix_exp", "svd_lowrank", "ormqr"]




def _mxu_precision(*arrays):
    """bf16/f16 operands must run at DEFAULT precision: the global
    "highest" setting (exact-ish f32 tests) would push them onto the
    multi-pass bf16x3/x6 algorithms, 3-6x slower on the MXU, defeating
    the point of reduced precision."""
    import jax
    for a in arrays:
        if hasattr(a, "dtype") and a.dtype in (jnp.bfloat16, jnp.float16):
            return jax.lax.Precision.DEFAULT
    return None


def matmul(x, y, transpose_x=False, transpose_y=False, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    def fn(a, b):
        if transpose_x and a.ndim >= 2:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y and b.ndim >= 2:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b, precision=_mxu_precision(a, b))
    return apply_op("matmul", fn, (x, y), {})


def mm(input, mat2, name=None) -> Tensor:
    return matmul(input, mat2)


def bmm(x, y, name=None) -> Tensor:
    return matmul(x, y)


def mv(x, vec, name=None) -> Tensor:
    return matmul(x, vec)


def dot(x, y, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("dot", lambda a, b: jnp.sum(a * b, axis=-1), (x, y), {})


def norm(x, p=None, axis=None, keepdim=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    pval = "fro" if p is None else p
    def fn(a):
        if axis is None and (pval == "fro" or pval == 2):
            return jnp.sqrt(jnp.sum(jnp.square(a)))
        if pval == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=keepdim))
        if pval == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if pval == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if pval == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** pval, axis=axis, keepdims=keepdim) ** (1.0 / pval)
    return apply_op("norm", fn, (x,), {})


def dist(x, y, p=2, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    def fn(a, b):
        d = a - b
        if p == 0:
            return jnp.sum((d != 0).astype(d.dtype)).astype(d.dtype)
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)
    return apply_op("dist", fn, (x, y), {})


def cond(x, p=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    pv = 2 if p is None else p
    return apply_op("cond", lambda a: jnp.linalg.cond(a, p=pv), (x,), {})


def cholesky(x, upper=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    def fn(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return apply_op("cholesky", fn, (x,), {})


def cholesky_solve(x, y, upper=False, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    def fn(b, l):
        lo = jnp.swapaxes(l, -1, -2) if upper else l
        z = jax.scipy.linalg.solve_triangular(lo, b, lower=True)
        return jax.scipy.linalg.solve_triangular(
            jnp.swapaxes(lo, -1, -2), z, lower=False)
    return apply_op("cholesky_solve", fn, (x, y), {})


def qr(x, mode="reduced", name=None):
    x = ensure_tensor(x)
    if mode == "r":
        return apply_op("qr_r", lambda a: jnp.linalg.qr(a, mode="r"), (x,), {})
    outs = apply_op("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), (x,), {})
    return outs[0], outs[1]


def svd(x, full_matrices=False, name=None):
    x = ensure_tensor(x)
    outs = apply_op(
        "svd", lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
        (x,), {})
    return outs[0], outs[1], outs[2]


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    x = ensure_tensor(x)
    m, n = x.shape[-2], x.shape[-1]
    qv = q if q is not None else min(6, m, n)
    def fn(a):
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        u, s, vt = jnp.linalg.svd(a, full_matrices=False)
        return u[..., :qv], s[..., :qv], jnp.swapaxes(vt, -1, -2)[..., :qv]
    outs = apply_op("pca_lowrank", fn, (x,), {})
    return outs[0], outs[1], outs[2]


def inv(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("inv", jnp.linalg.inv, (x,), {})


def pinv(x, rcond=1e-15, hermitian=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("pinv",
                    lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian),
                    (x,), {})


def det(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("det", jnp.linalg.det, (x,), {})


def slogdet(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    outs = apply_op("slogdet", lambda a: tuple(jnp.linalg.slogdet(a)), (x,), {})
    # paddle returns stacked [sign, logdet]
    from .manipulation import stack
    return stack([outs[0], outs[1]], axis=0)


def solve(x, y, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    def fn(a, b):
        squeeze = b.ndim == a.ndim - 1
        if squeeze:
            b = b[..., None]
        out = jnp.linalg.solve(a, b)
        return out[..., 0] if squeeze else out
    return apply_op("solve", fn, (x, y), {})


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op(
        "triangular_solve",
        lambda a, b: jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular),
        (x, y), {})


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    outs = apply_op("lstsq",
                    lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)),
                    (x, y), {})
    return tuple(outs)


def eig(x, name=None):
    x = ensure_tensor(x)
    w, v = np.linalg.eig(np.asarray(x._data))  # complex eig: host LAPACK
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    w = np.linalg.eigvals(np.asarray(x._data))
    return Tensor(jnp.asarray(w))


def eigh(x, UPLO="L", name=None):
    x = ensure_tensor(x)
    outs = apply_op("eigh",
                    lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), (x,), {})
    return outs[0], outs[1]


def eigvalsh(x, UPLO="L", name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO),
                    (x,), {})


def matrix_power(x, n, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("matrix_power", lambda a: jnp.linalg.matrix_power(a, n),
                    (x,), {})


def matrix_rank(x, tol=None, hermitian=False, atol=None, rtol=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("matrix_rank",
                    lambda a: jnp.linalg.matrix_rank(a, tol=tol),
                    (x,), {}, differentiable=False)


def multi_dot(x, name=None) -> Tensor:
    ts = [ensure_tensor(t) for t in x]
    return apply_op("multi_dot", lambda *xs: jnp.linalg.multi_dot(xs),
                    tuple(ts), {})


def corrcoef(x, rowvar=True, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), (x,), {})


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    fw = fweights._data if isinstance(fweights, Tensor) else fweights
    aw = aweights._data if isinstance(aweights, Tensor) else aweights
    return apply_op("cov",
                    lambda a: jnp.cov(a, rowvar=rowvar,
                                      ddof=1 if ddof else 0,
                                      fweights=fw, aweights=aw),
                    (x,), {})


def householder_product(x, tau, name=None) -> Tensor:
    x, tau = ensure_tensor(x), ensure_tensor(tau)
    def fn(a, t):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(q, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else q
        for i in range(t.shape[-1]):
            v = jnp.concatenate([
                jnp.zeros(a.shape[:-2] + (i,), a.dtype),
                jnp.ones(a.shape[:-2] + (1,), a.dtype),
                a[..., i + 1:, i]], axis=-1)
            ti = t[..., i:i + 1]
            h = - ti[..., None] * (v[..., :, None] * v[..., None, :])
            q = q + jnp.matmul(q, h)
        return q[..., :, :n]
    return apply_op("householder_product", fn, (x, tau), {})


def lu(x, pivot=True, get_infos=False, name=None):
    x = ensure_tensor(x)
    lu_mat, piv = jax.scipy.linalg.lu_factor(x._data)
    outs = (Tensor(lu_mat), Tensor((piv + 1).astype(jnp.int32)))
    if get_infos:
        return outs + (Tensor(jnp.zeros((), jnp.int32)),)
    return outs


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    a, piv = np.asarray(x._data), np.asarray(y._data) - 1
    m, n = a.shape[-2], a.shape[-1]
    k = min(m, n)
    l = np.tril(a[..., :, :k], -1) + np.eye(m, k, dtype=a.dtype)
    u = np.triu(a[..., :k, :])
    p = np.eye(m, dtype=a.dtype)
    for i, pv in enumerate(piv):
        row = p[i].copy(); p[i] = p[pv]; p[pv] = row
    return Tensor(jnp.asarray(p.T)), Tensor(jnp.asarray(l)), Tensor(jnp.asarray(u))


def einsum(equation, *operands) -> Tensor:
    ts = [ensure_tensor(o) for o in operands]
    return apply_op("einsum",
                    lambda *xs: jnp.einsum(
                        equation, *xs, precision=_mxu_precision(*xs)),
                    tuple(ts), {})



def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None) -> Tensor:
    """linalg.vector_norm parity."""
    x = ensure_tensor(x)

    def f(a):
        if axis is None:
            out = jnp.linalg.norm(a.reshape(-1), ord=p)
            if keepdim:
                out = out.reshape((1,) * a.ndim)
            return out
        return jnp.linalg.norm(a, ord=p, axis=axis, keepdims=keepdim)
    return apply_op("vector_norm", f, (x,), {})


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None) -> Tensor:
    """linalg.matrix_norm parity."""
    x = ensure_tensor(x)
    return apply_op("matrix_norm",
                    lambda a: jnp.linalg.norm(a, ord=p, axis=tuple(axis),
                                              keepdims=keepdim), (x,), {})


def cholesky_inverse(x, upper=False, name=None) -> Tensor:
    """linalg.cholesky_inverse: inverse from a Cholesky factor."""
    x = ensure_tensor(x)

    def f(L):
        A = L.T @ L if upper else L @ L.T
        return jnp.linalg.inv(A)
    return apply_op("cholesky_inverse", f, (x,), {})


def matrix_exp(x, name=None) -> Tensor:
    """linalg.matrix_exp via jax.scipy.linalg.expm."""
    from jax.scipy.linalg import expm
    return apply_op("matrix_exp", expm, (ensure_tensor(x),), {})


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """linalg.svd_lowrank: randomized range finder + small SVD."""
    import numpy as _np
    x = ensure_tensor(x)
    if M is not None:
        from .math import subtract
        x = subtract(x, ensure_tensor(M))
    m, n = int(x.shape[-2]), int(x.shape[-1])
    k = min(q, m, n)
    omega = jnp.asarray(_np.random.RandomState(0).randn(n, k),
                        x._data.dtype)

    def f(a):
        aT = jnp.swapaxes(a, -2, -1)  # batched-safe transpose
        y = a @ omega
        for _ in range(niter):
            y = a @ (aT @ y)
        Q, _ = jnp.linalg.qr(y)
        B = jnp.swapaxes(Q, -2, -1) @ a
        u, s, vt = jnp.linalg.svd(B, full_matrices=False)
        return Q @ u, s, jnp.swapaxes(vt, -2, -1)
    return apply_op("svd_lowrank", f, (x,), {})


def ormqr(x, tau, y, left=True, transpose=False, name=None) -> Tensor:
    """linalg.ormqr: multiply by Q from a QR (householder) factorization.
    Materializes Q via householder_product — O(mn^2), fine for the sizes
    this API is used at."""
    q = householder_product(x, tau)

    def f(qm, ym):
        qq = jnp.swapaxes(qm, -2, -1) if transpose else qm
        return qq @ ym if left else ym @ qq
    return apply_op("ormqr", f, (ensure_tensor(q), ensure_tensor(y)), {})
