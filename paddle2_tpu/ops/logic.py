"""Comparison / logic / search ops (python/paddle/tensor/{logic,search}.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .dispatch import apply_op, ensure_tensor
from ..framework import core
from ..framework.tensor import Tensor

__all__ = ["equal", "not_equal", "greater_than", "greater_equal", "less_than",
           "less_equal", "equal_all", "allclose", "isclose", "is_empty",
           "is_tensor", "argmax", "argmin", "topk", "kthvalue", "mode",
           "searchsorted", "bucketize", "index_fill", "index_fill_", "masked_scatter"]


def _cmp(name, jfn):
    def op(x, y, name_arg=None):
        x = ensure_tensor(x, y if isinstance(y, Tensor) else None)
        y = ensure_tensor(y, x)
        return apply_op(name, jfn, (x, y), {}, differentiable=False)
    op.__name__ = name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)


def equal_all(x, y, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    if tuple(x.shape) != tuple(y.shape):
        return Tensor(jnp.asarray(False))
    return apply_op("equal_all", lambda a, b: jnp.all(a == b), (x, y), {},
                    differentiable=False)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("allclose",
                    lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                              equal_nan=equal_nan),
                    (x, y), {}, differentiable=False)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("isclose",
                    lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                             equal_nan=equal_nan),
                    (x, y), {}, differentiable=False)


def is_empty(x, name=None) -> Tensor:
    return Tensor(jnp.asarray(ensure_tensor(x).size == 0))


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None) -> Tensor:
    x = ensure_tensor(x)
    dt = core.convert_dtype(dtype)
    def fn(a):
        out = jnp.argmax(a.reshape(-1) if axis is None else a,
                         axis=None if axis is None else axis,
                         keepdims=keepdim if axis is not None else False)
        return out.astype(dt)
    return apply_op("argmax", fn, (x,), {}, differentiable=False)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None) -> Tensor:
    x = ensure_tensor(x)
    dt = core.convert_dtype(dtype)
    def fn(a):
        out = jnp.argmin(a.reshape(-1) if axis is None else a,
                         axis=None if axis is None else axis,
                         keepdims=keepdim if axis is not None else False)
        return out.astype(dt)
    return apply_op("argmin", fn, (x,), {}, differentiable=False)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = ensure_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.item())
    ax = -1 if axis is None else axis
    def fn(a):
        moved = jnp.moveaxis(a, ax, -1)
        src = moved if largest else -moved
        vals, idx = jax.lax.top_k(src, k)
        if not largest:
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax))
    values_indices = apply_op("topk", fn, (x,), {})
    vals, idx = values_indices
    idx_t = Tensor(idx._data.astype(jnp.int32))
    return vals, idx_t


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)
    def fn(a):
        srt = jnp.sort(a, axis=axis)
        sidx = jnp.argsort(a, axis=axis)
        vals = jnp.take(srt, k - 1, axis=axis)
        idx = jnp.take(sidx, k - 1, axis=axis)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx
    vals, idx = apply_op("kthvalue", fn, (x,), {})
    return vals, Tensor(idx._data.astype(jnp.int32))


def mode(x, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)
    a = np.asarray(x._data)
    moved = np.moveaxis(a, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], a.dtype)
    idxs = np.empty(flat.shape[0], np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        v = uniq[np.argmax(counts)]
        vals[i] = v
        idxs[i] = np.where(row == v)[0][-1]
    shape = moved.shape[:-1]
    vals = vals.reshape(shape)
    idxs = idxs.reshape(shape)
    if keepdim:
        vals = np.expand_dims(vals, axis)
        idxs = np.expand_dims(idxs, axis)
    return Tensor(jnp.asarray(vals)), Tensor(jnp.asarray(idxs))


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None) -> Tensor:
    ss, v = ensure_tensor(sorted_sequence), ensure_tensor(values)
    side = "right" if right else "left"
    dt = jnp.int32 if out_int32 else jnp.int32
    def fn(a, b):
        if a.ndim == 1:
            return jnp.searchsorted(a, b, side=side).astype(dt)
        flat_a = a.reshape(-1, a.shape[-1])
        flat_b = b.reshape(-1, b.shape[-1])
        out = jnp.stack([jnp.searchsorted(fa, fb, side=side)
                         for fa, fb in zip(flat_a, flat_b)])
        return out.reshape(b.shape).astype(dt)
    return apply_op("searchsorted", fn, (ss, v), {}, differentiable=False)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None) -> Tensor:
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def index_fill(x, index, axis, value, name=None) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)
    def fn(a, i):
        moved = jnp.moveaxis(a, axis, 0)
        out = moved.at[i.reshape(-1)].set(value)
        return jnp.moveaxis(out, 0, axis)
    return apply_op("index_fill", fn, (x, index), {})


def masked_scatter(x, mask, value, name=None) -> Tensor:
    x, mask, value = ensure_tensor(x), ensure_tensor(mask), ensure_tensor(value)
    a, m, v = (np.asarray(x._data), np.asarray(mask._data),
               np.asarray(value._data).reshape(-1))
    m = np.broadcast_to(m, a.shape)
    out = a.copy()
    out[m] = v[:int(m.sum())]
    return Tensor(jnp.asarray(out))


def index_fill_(x, index, axis, value, name=None) -> Tensor:
    """Inplace index_fill (tensor.py index_fill_)."""
    from .dispatch import rebind_inplace
    return rebind_inplace(x, index_fill(x, index, axis, value))
