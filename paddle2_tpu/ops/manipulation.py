"""Shape/layout manipulation ops (python/paddle/tensor/manipulation.py parity).

All views are functional on TPU (XLA has no aliasing across op boundaries);
"inplace_" variants rebind the Tensor's payload, matching eager semantics.
"""

from __future__ import annotations

import builtins
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .dispatch import apply_op, ensure_tensor, rebind_inplace
from ..framework import core
from ..framework.tensor import Tensor

__all__ = ["reshape", "reshape_", "transpose", "t", "flatten", "squeeze",
           "squeeze_", "unsqueeze", "unsqueeze_", "concat", "stack", "split",
           "chunk", "tile", "expand", "expand_as", "broadcast_to",
           "broadcast_tensors", "flip", "rot90", "roll", "gather", "gather_nd",
           "scatter", "scatter_", "scatter_nd", "scatter_nd_add", "index_select",
           "index_sample", "index_add", "index_add_", "index_put_", "index_put", "masked_select",
           "masked_fill", "where", "nonzero", "take_along_axis", "put_along_axis",
           "unbind", "repeat_interleave", "unique", "unique_consecutive",
           "sort", "argsort", "slice", "strided_slice", "moveaxis", "swapaxes",
           "as_complex", "as_real", "cast", "numel", "shard_index",
           "unstack", "unfold", "tensordot", "atleast_1d", "atleast_2d",
           "atleast_3d", "view", "view_as", "tolist", "crop", "pad_basic"]


def _axes(axis):
    if isinstance(axis, Tensor):
        return tuple(int(a) for a in axis.numpy().reshape(-1))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def reshape(x, shape, name=None) -> Tensor:
    x = ensure_tensor(x)
    if isinstance(shape, Tensor):
        shape = tuple(int(s) for s in shape.numpy().reshape(-1))
    else:
        shape = tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                      for s in shape)
    return apply_op("reshape", lambda a: jnp.reshape(a, shape), (x,), {})


def reshape_(x, shape, name=None) -> Tensor:
    return rebind_inplace(x, reshape(x, shape))


def transpose(x, perm, name=None) -> Tensor:
    x = ensure_tensor(x)
    perm = tuple(int(p) for p in perm)
    return apply_op("transpose", lambda a: jnp.transpose(a, perm), (x,), {})


def t(input, name=None) -> Tensor:
    input = ensure_tensor(input)
    if input.ndim < 2:
        return input.clone()
    if input.ndim == 2:
        return apply_op("t", lambda a: a.T, (input,), {})
    raise ValueError("paddle.t only supports ndim<=2; use transpose")


def flatten(x, start_axis=0, stop_axis=-1, name=None) -> Tensor:
    x = ensure_tensor(x)
    nd = builtins.max(x.ndim, 1)
    s = start_axis % nd
    e = stop_axis % nd
    def fn(a):
        if a.ndim == 0:
            return a.reshape(1)
        shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return a.reshape(shape)
    return apply_op("flatten", fn, (x,), {})


def squeeze(x, axis=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    def fn(a):
        if axis is None:
            return jnp.squeeze(a)
        ax = _axes(axis)
        ax = (ax,) if isinstance(ax, int) else ax
        ax = tuple(a_ % a.ndim for a_ in ax if a.shape[a_ % a.ndim] == 1)
        return jnp.squeeze(a, axis=ax) if ax else a
    return apply_op("squeeze", fn, (x,), {})


def squeeze_(x, axis=None, name=None) -> Tensor:
    return rebind_inplace(x, squeeze(x, axis))


def unsqueeze(x, axis, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = _axes(axis)
    ax = (ax,) if isinstance(ax, int) else ax
    return apply_op("unsqueeze", lambda a: jnp.expand_dims(a, ax), (x,), {})


def unsqueeze_(x, axis, name=None) -> Tensor:
    return rebind_inplace(x, unsqueeze(x, axis))


def concat(x, axis=0, name=None) -> Tensor:
    ts = [ensure_tensor(t_) for t_ in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op("concat", lambda *xs: jnp.concatenate(xs, axis=axis),
                    tuple(ts), {})


def stack(x, axis=0, name=None) -> Tensor:
    ts = [ensure_tensor(t_) for t_ in x]
    return apply_op("stack", lambda *xs: jnp.stack(xs, axis=axis), tuple(ts), {})


def split(x, num_or_sections, axis=0, name=None) -> List[Tensor]:
    x = ensure_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: axis dim {dim} is not divisible by "
                f"num_or_sections={num_or_sections}")
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = [int(s.item()) if isinstance(s, Tensor) else int(s)
                    for s in num_or_sections]
        neg = [i for i, s in enumerate(sections) if s < 0]
        if neg:
            sections[neg[0]] = dim - builtins.sum(s for s in sections if s >= 0)
    offsets = np.cumsum([0] + sections).tolist()
    def fn(a):
        return tuple(jax.lax.slice_in_dim(a, offsets[i], offsets[i + 1], axis=axis)
                     for i in range(len(sections)))
    return list(apply_op("split", fn, (x,), {}))


def chunk(x, chunks, axis=0, name=None) -> List[Tensor]:
    return split(x, chunks, axis)


def unstack(x, axis=0, num=None) -> List[Tensor]:
    x = ensure_tensor(x)
    n = num or x.shape[axis]
    def fn(a):
        return tuple(jnp.squeeze(s, axis=axis)
                     for s in jnp.split(a, n, axis=axis))
    return list(apply_op("unstack", fn, (x,), {}))


def unbind(input, axis=0) -> List[Tensor]:
    return unstack(input, axis)


def tile(x, repeat_times, name=None) -> Tensor:
    x = ensure_tensor(x)
    if isinstance(repeat_times, Tensor):
        repeat_times = tuple(int(r) for r in repeat_times.numpy().reshape(-1))
    else:
        repeat_times = tuple(int(r.item()) if isinstance(r, Tensor) else int(r)
                             for r in repeat_times)
    return apply_op("tile", lambda a: jnp.tile(a, repeat_times), (x,), {})


def _resolve_expand_shape(x, shape):
    if isinstance(shape, Tensor):
        shape = [int(s) for s in shape.numpy().reshape(-1)]
    shape = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]
    nd = len(shape)
    xs = [1] * (nd - len(x.shape)) + list(x.shape)
    return tuple(xs[i] if shape[i] == -1 else shape[i] for i in range(nd))


def expand(x, shape, name=None) -> Tensor:
    x = ensure_tensor(x)
    target = _resolve_expand_shape(x, shape)
    return apply_op("expand", lambda a: jnp.broadcast_to(a, target), (x,), {})


def expand_as(x, y, name=None) -> Tensor:
    y = ensure_tensor(y)
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None) -> Tensor:
    return expand(x, shape)


def broadcast_tensors(input, name=None) -> List[Tensor]:
    ts = [ensure_tensor(t_) for t_ in input]
    shape = np.broadcast_shapes(*[tuple(t_.shape) for t_ in ts])
    return [expand(t_, list(shape)) for t_ in ts]


def flip(x, axis, name=None) -> Tensor:
    x = ensure_tensor(x)
    ax = _axes(axis)
    return apply_op("flip", lambda a: jnp.flip(a, axis=ax), (x,), {})


def rot90(x, k=1, axes=(0, 1), name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), (x,), {})


def roll(x, shifts, axis=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    if isinstance(shifts, Tensor):
        shifts = tuple(int(s) for s in shifts.numpy().reshape(-1))
    ax = _axes(axis) if axis is not None else None
    return apply_op("roll", lambda a: jnp.roll(a, shifts, axis=ax), (x,), {})


def gather(x, index, axis=0, name=None) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op("gather",
                    lambda a, i: jnp.take(a, i.reshape(-1), axis=axis),
                    (x, index), {})


def gather_nd(x, index, name=None) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)
    def fn(a, i):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a[idx]
    return apply_op("gather_nd", fn, (x, index), {})


def scatter(x, index, updates, overwrite=True, name=None) -> Tensor:
    x, index, updates = (ensure_tensor(x), ensure_tensor(index),
                         ensure_tensor(updates))
    def fn(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        # paddle: non-overwrite zeroes target rows then accumulates
        zeroed = a.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)
    return apply_op("scatter", fn, (x, index, updates), {})


def scatter_(x, index, updates, overwrite=True, name=None) -> Tensor:
    return rebind_inplace(x, scatter(x, index, updates, overwrite))


def scatter_nd(index, updates, shape, name=None) -> Tensor:
    index, updates = ensure_tensor(index), ensure_tensor(updates)
    shape = tuple(int(s) for s in (shape.numpy().reshape(-1)
                                   if isinstance(shape, Tensor) else shape))
    def fn(i, u):
        zero = jnp.zeros(shape, u.dtype)
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return zero.at[idx].add(u)
    return apply_op("scatter_nd", fn, (index, updates), {})


def scatter_nd_add(x, index, updates, name=None) -> Tensor:
    x, index, updates = (ensure_tensor(x), ensure_tensor(index),
                         ensure_tensor(updates))
    def fn(a, i, u):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a.at[idx].add(u)
    return apply_op("scatter_nd_add", fn, (x, index, updates), {})


def index_select(x, index, axis=0, name=None) -> Tensor:
    return gather(x, index, axis)


def index_sample(x, index) -> Tensor:
    x, index = ensure_tensor(x), ensure_tensor(index)
    return apply_op("index_sample",
                    lambda a, i: jnp.take_along_axis(a, i, axis=1),
                    (x, index), {})


def index_add(x, index, axis, value, name=None) -> Tensor:
    x, index, value = ensure_tensor(x), ensure_tensor(index), ensure_tensor(value)
    def fn(a, i, v):
        moved = jnp.moveaxis(a, axis, 0)
        vmoved = jnp.moveaxis(v, axis, 0)
        out = moved.at[i.reshape(-1)].add(vmoved)
        return jnp.moveaxis(out, 0, axis)
    return apply_op("index_add", fn, (x, index, value), {})


def index_put(x, indices, value, accumulate=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    value = ensure_tensor(value)
    idx_ts = tuple(ensure_tensor(i) for i in indices)
    def fn(a, v, *idx):
        if accumulate:
            return a.at[tuple(idx)].add(v)
        return a.at[tuple(idx)].set(v)
    return apply_op("index_put", fn, (x, value) + idx_ts, {})


def _require_eager(op_name: str, *tensors) -> None:
    import jax
    for t in tensors:
        if isinstance(t._data, jax.core.Tracer):
            raise RuntimeError(
                f"{op_name} has a data-dependent output shape and cannot run "
                "under jit.to_static tracing; compute it in eager mode "
                "(reference to_static has the same dynamic-shape limit)")


def masked_select(x, mask, name=None) -> Tensor:
    # data-dependent output shape: the *index* is computed eagerly with numpy,
    # then the gather itself goes through apply_op so the op is differentiable
    # (reference masked_select_grad scatters into zeros).
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    _require_eager("masked_select", x, mask)
    mask_np = np.broadcast_to(np.asarray(mask._data).astype(bool),
                              tuple(x._data.shape))
    idx = jnp.asarray(np.flatnonzero(mask_np))
    return apply_op("masked_select",
                    lambda a: jnp.take(a.reshape(-1), idx), (x,), {})


def masked_fill(x, mask, value, name=None) -> Tensor:
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    if isinstance(value, Tensor):
        return apply_op("masked_fill",
                        lambda a, m, v: jnp.where(m, v.astype(a.dtype), a),
                        (x, mask, value), {})
    return apply_op("masked_fill", lambda a, m: jnp.where(m, value, a),
                    (x, mask), {})


def where(condition, x=None, y=None, name=None):
    condition = ensure_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    x, y = ensure_tensor(x, y if isinstance(y, Tensor) else None), ensure_tensor(y, x if isinstance(x, Tensor) else None)
    return apply_op("where", lambda c, a, b: jnp.where(c, a, b),
                    (condition, x, y), {})


def nonzero(x, as_tuple=False):
    x = ensure_tensor(x)
    _require_eager("nonzero", x)
    nz = np.nonzero(np.asarray(x._data))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i)) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def take_along_axis(arr, indices, axis, broadcast=True) -> Tensor:
    arr, indices = ensure_tensor(arr), ensure_tensor(indices)
    return apply_op("take_along_axis",
                    lambda a, i: jnp.take_along_axis(a, i, axis=axis),
                    (arr, indices), {})


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True) -> Tensor:
    arr, indices = ensure_tensor(arr), ensure_tensor(indices)
    values = ensure_tensor(values)
    def fn(a, i, v):
        v = jnp.broadcast_to(v, i.shape) if v.ndim else jnp.full(i.shape, v, a.dtype)
        dims = [jnp.arange(s).reshape([-1 if k == d else 1
                                        for k in range(a.ndim)])
                for d, s in enumerate(i.shape)]
        full_idx = tuple(i if d == axis else jnp.broadcast_to(dims[d], i.shape)
                         for d in range(a.ndim))
        if reduce == "add":
            return a.at[full_idx].add(v)
        if reduce == "multiply" or reduce == "mul":
            return a.at[full_idx].multiply(v)
        return a.at[full_idx].set(v)
    return apply_op("put_along_axis", fn, (arr, indices, values), {})


def repeat_interleave(x, repeats, axis=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    if isinstance(repeats, Tensor):
        reps = jnp.asarray(repeats._data)
        total = int(np.sum(repeats.numpy()))
        return apply_op("repeat_interleave",
                        lambda a: jnp.repeat(a, reps, axis=axis,
                                             total_repeat_length=total),
                        (x,), {})
    return apply_op("repeat_interleave",
                    lambda a: jnp.repeat(a, repeats, axis=axis), (x,), {})


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    res = np.unique(np.asarray(x._data), return_index=return_index,
                    return_inverse=return_inverse, return_counts=return_counts,
                    axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    x = ensure_tensor(x)
    a = np.asarray(x._data)
    if axis is None:
        a = a.reshape(-1)
        keep = np.concatenate([[True], a[1:] != a[:-1]])
        vals = a[keep]
    else:
        if a.shape[axis] == 0:
            keep = np.zeros(0, bool)
        else:
            diff = np.any(np.diff(a, axis=axis) != 0,
                          axis=tuple(i for i in range(a.ndim) if i != axis))
            keep = np.concatenate([[True], diff])
        vals = np.take(a, np.nonzero(keep)[0], axis=axis)
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv)))
    if return_counts:
        idx = np.nonzero(np.concatenate([keep, [True]]))[0]
        outs.append(Tensor(jnp.asarray(np.diff(idx))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def sort(x, axis=-1, descending=False, stable=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    def fn(a):
        out = jnp.sort(a, axis=axis, stable=True)
        return jnp.flip(out, axis=axis) if descending else out
    return apply_op("sort", fn, (x,), {})


def argsort(x, axis=-1, descending=False, stable=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    def fn(a):
        idx = jnp.argsort(a, axis=axis, stable=True)
        return jnp.flip(idx, axis=axis) if descending else idx
    return apply_op("argsort", fn, (x,), {}, differentiable=False)


def slice(input, axes, starts, ends) -> Tensor:
    input = ensure_tensor(input)
    def _v(s):
        return int(s.item()) if isinstance(s, Tensor) else int(s)
    starts = [_v(s) for s in starts]
    ends = [_v(e) for e in ends]
    def fn(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            idx[ax] = builtins.slice(s, e)
        return a[tuple(idx)]
    return apply_op("slice", fn, (input,), {})


def strided_slice(x, axes, starts, ends, strides, name=None) -> Tensor:
    x = ensure_tensor(x)
    def fn(a):
        idx = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = builtins.slice(int(s), int(e), int(st))
        return a[tuple(idx)]
    return apply_op("strided_slice", fn, (x,), {})


def crop(x, shape=None, offsets=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    shape = [int(s) for s in (shape or x.shape)]
    offsets = [int(o) for o in (offsets or [0] * x.ndim)]
    def fn(a):
        idx = tuple(builtins.slice(o, o + s) for o, s in zip(offsets, shape))
        return a[idx]
    return apply_op("crop", fn, (x,), {})


def moveaxis(x, source, destination, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("moveaxis", lambda a: jnp.moveaxis(a, source, destination),
                    (x,), {})


def swapaxes(x, axis0, axis1, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("swapaxes", lambda a: jnp.swapaxes(a, axis0, axis1), (x,), {})


def as_complex(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("as_complex",
                    lambda a: jax.lax.complex(a[..., 0], a[..., 1]), (x,), {})


def as_real(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return apply_op("as_real",
                    lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1),
                    (x,), {})


def cast(x, dtype) -> Tensor:
    return ensure_tensor(x).astype(dtype)


def numel(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jnp.asarray(x.size, dtype=jnp.int32))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    input = ensure_tensor(input)
    shard_size = (index_num + nshards - 1) // nshards
    def fn(a):
        lo, hi = shard_id * shard_size, (shard_id + 1) * shard_size
        inside = (a >= lo) & (a < hi)
        return jnp.where(inside, a - lo, ignore_value)
    return apply_op("shard_index", fn, (input,), {}, differentiable=False)


def unfold(x, axis, size, step, name=None) -> Tensor:
    x = ensure_tensor(x)
    def fn(a):
        n = (a.shape[axis] - size) // step + 1
        idx = jnp.arange(n)[:, None] * step + jnp.arange(size)[None, :]
        moved = jnp.moveaxis(a, axis, -1)
        win = moved[..., idx]  # (..., n, size)
        return jnp.moveaxis(win, -2, axis)
    return apply_op("unfold", fn, (x,), {})


def tensordot(x, y, axes=2, name=None) -> Tensor:
    x, y = ensure_tensor(x), ensure_tensor(y)
    if isinstance(axes, Tensor):
        axes = axes.tolist()
    return apply_op("tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes),
                    (x, y), {})


def atleast_1d(*inputs, name=None):
    outs = [apply_op("atleast_1d", jnp.atleast_1d, (ensure_tensor(i),), {})
            for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op("atleast_2d", jnp.atleast_2d, (ensure_tensor(i),), {})
            for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op("atleast_3d", jnp.atleast_3d, (ensure_tensor(i),), {})
            for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def view(x, shape_or_dtype, name=None) -> Tensor:
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    x = ensure_tensor(x)
    dt = core.convert_dtype(shape_or_dtype)
    return apply_op("view_dtype", lambda a: a.view(dt), (x,), {},
                    differentiable=False)


def view_as(x, other, name=None) -> Tensor:
    return reshape(x, ensure_tensor(other).shape)


def tolist(x):
    return ensure_tensor(x).tolist()


def pad_basic(x, pad, value=0.0):
    x = ensure_tensor(x)
    cfg = [(int(pad[2 * i]), int(pad[2 * i + 1])) for i in range(len(pad) // 2)]
    cfg = [(0, 0)] * (x.ndim - len(cfg)) + cfg
    return apply_op("pad", lambda a: jnp.pad(a, cfg, constant_values=value),
                    (x,), {})


def index_add_(x, index, axis, value, name=None) -> Tensor:
    """Inplace index_add (tensor.py index_add_)."""
    return rebind_inplace(x, index_add(x, index, axis, value))


def index_put_(x, indices, value, accumulate=False, name=None) -> Tensor:
    return rebind_inplace(x, index_put(x, indices, value, accumulate))
