"""Elementwise + reduction math ops (python/paddle/tensor/math.py parity).

Each op is a thin Tensor wrapper over a pure jnp function; XLA fuses chains of
these into single TPU kernels under jit, and the eager path records the tape
via dispatch.apply_op. Reference: op list from paddle/phi/ops/yaml/ops.yaml.
"""

from __future__ import annotations

import builtins
import math as _pymath
from functools import partial
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp_special

from .dispatch import apply_op, ensure_tensor
from ..framework import core
from ..framework.tensor import Tensor

__all__ = []


def _export(name, fn):
    globals()[name] = fn
    __all__.append(name)
    return fn


def _unary(name, jfn, differentiable=True):
    def op(x, name=None):  # noqa: A002 — paddle API takes `name`
        return apply_op(op.__name__, jfn, (ensure_tensor(x),), {},
                        differentiable=differentiable)
    op.__name__ = name
    op.__qualname__ = name
    return _export(name, op)


def _binary(name, jfn, differentiable=True):
    def op(x, y, name=None):  # noqa: A002
        x = ensure_tensor(x, y if isinstance(y, Tensor) else None)
        y = ensure_tensor(y, x)
        return apply_op(op.__name__, jfn, (x, y), {},
                        differentiable=differentiable)
    op.__name__ = name
    op.__qualname__ = name
    return _export(name, op)


# ---------------------------------------------------------------------------
# unary
# ---------------------------------------------------------------------------
_unary("abs", jnp.abs)
_unary("acos", jnp.arccos)
_unary("acosh", jnp.arccosh)
_unary("asin", jnp.arcsin)
_unary("asinh", jnp.arcsinh)
_unary("atan", jnp.arctan)
_unary("atanh", jnp.arctanh)
_unary("ceil", jnp.ceil)
_unary("cos", jnp.cos)
_unary("cosh", jnp.cosh)
_unary("digamma", jsp_special.digamma)
_unary("erf", jax.lax.erf)
_unary("erfinv", jax.lax.erf_inv)
_unary("exp", jnp.exp)
_unary("expm1", jnp.expm1)
_unary("floor", jnp.floor)
_unary("lgamma", jsp_special.gammaln)
_unary("log", jnp.log)
_unary("log2", jnp.log2)
_unary("log10", jnp.log10)
_unary("log1p", jnp.log1p)
_unary("neg", jnp.negative)
_unary("reciprocal", jnp.reciprocal)
_unary("round", jnp.round)
_unary("rsqrt", jax.lax.rsqrt)
_unary("sigmoid", jax.nn.sigmoid)
_unary("sign", jnp.sign)
_unary("sin", jnp.sin)
_unary("sinh", jnp.sinh)
_unary("sqrt", jnp.sqrt)
_unary("square", jnp.square)
_unary("tan", jnp.tan)
_unary("tanh", jnp.tanh)
_unary("trunc", jnp.trunc)
_unary("frac", lambda x: x - jnp.trunc(x))
_unary("angle", jnp.angle)
_unary("conj", jnp.conj)
_unary("i0", jsp_special.i0)
_unary("i0e", jsp_special.i0e)
_unary("i1", jsp_special.i1)
_unary("i1e", jsp_special.i1e)
_unary("isnan", jnp.isnan, differentiable=False)
_unary("isinf", jnp.isinf, differentiable=False)
_unary("isfinite", jnp.isfinite, differentiable=False)
_unary("bitwise_not", jnp.bitwise_not, differentiable=False)
_unary("logit", jsp_special.logit)
_unary("deg2rad", jnp.deg2rad)
_unary("rad2deg", jnp.rad2deg)
_unary("exponential_", lambda x: x)  # placeholder; random fills in random.py


def logical_not(x, out=None, name=None):
    return apply_op("logical_not", jnp.logical_not, (ensure_tensor(x),), {},
                    differentiable=False)
_export("logical_not", logical_not)


# ---------------------------------------------------------------------------
# binary
# ---------------------------------------------------------------------------
_binary("add", jnp.add)
_binary("subtract", jnp.subtract)
_binary("multiply", jnp.multiply)
_binary("divide", jnp.divide)
_binary("floor_divide", jnp.floor_divide, differentiable=False)
_binary("remainder", jnp.remainder)
_binary("mod", jnp.remainder)
_binary("floor_mod", jnp.remainder)
_binary("pow_op", jnp.power)
_binary("maximum", jnp.maximum)
_binary("minimum", jnp.minimum)
_binary("fmax", jnp.fmax)
_binary("fmin", jnp.fmin)
_binary("atan2", jnp.arctan2)
_binary("logaddexp", jnp.logaddexp)
_binary("heaviside", jnp.heaviside)
_binary("hypot", jnp.hypot)
_binary("copysign", jnp.copysign)
_binary("nextafter", jnp.nextafter, differentiable=False)
_binary("gcd", jnp.gcd, differentiable=False)
_binary("lcm", jnp.lcm, differentiable=False)
_binary("ldexp", lambda x, y: x * (2.0 ** y))
_binary("polygamma_n", lambda x, n: jsp_special.polygamma(n, x))
_binary("logical_and", jnp.logical_and, differentiable=False)
_binary("logical_or", jnp.logical_or, differentiable=False)
_binary("logical_xor", jnp.logical_xor, differentiable=False)
_binary("bitwise_and", jnp.bitwise_and, differentiable=False)
_binary("bitwise_or", jnp.bitwise_or, differentiable=False)
_binary("bitwise_xor", jnp.bitwise_xor, differentiable=False)


def pow(x, y, name=None):
    if isinstance(y, int) and not isinstance(y, bool):
        x = ensure_tensor(x)
        return apply_op("pow", lambda a: jax.lax.integer_pow(a, y), (x,), {})
    return pow_op(x, y)  # noqa: F821
_export("pow", pow)


def divide_no_nan(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("divide_no_nan",
                    lambda a, b: jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b)),
                    (x, y), {})
_export("divide_no_nan", divide_no_nan)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = ensure_tensor(x)
    def fn(a):
        out = a * scale + bias if bias_after_scale else (a + bias) * scale
        return out
    out = apply_op("scale", fn, (x,), {})
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out
_export("scale", scale)


def clip(x, min=None, max=None, name=None):
    x = ensure_tensor(x)
    lo = float(min) if isinstance(min, (int, float)) else (min._data if min is not None else None)
    hi = float(max) if isinstance(max, (int, float)) else (max._data if max is not None else None)
    return apply_op("clip", lambda a: jnp.clip(a, lo, hi), (x,), {})
_export("clip", clip)


def lerp(x, y, weight, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if isinstance(weight, Tensor):
        return apply_op("lerp", lambda a, b, w: a + w * (b - a), (x, y, weight), {})
    return apply_op("lerp", lambda a, b: a + weight * (b - a), (x, y), {})
_export("lerp", lerp)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    x = ensure_tensor(x)
    return apply_op("nan_to_num",
                    lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
                    (x,), {})
_export("nan_to_num", nan_to_num)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    x = ensure_tensor(x)
    return apply_op("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), (x,), {})
_export("stanh", stanh)


def multiplex(inputs, index, name=None):
    ts = [ensure_tensor(t) for t in inputs]
    index = ensure_tensor(index)
    def fn(idx, *xs):
        stacked = jnp.stack(xs, axis=0)
        return jnp.take_along_axis(
            stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0)[0]
    return apply_op("multiplex", fn, (index, *ts), {})
_export("multiplex", multiplex)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------

def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if isinstance(axis, Tensor):
        return tuple(int(a) for a in axis.numpy().reshape(-1))
    return int(axis)


def _reduction(name, jfn, differentiable=True, dtype_arg=False):
    opname = name
    def op(x, axis=None, keepdim=False, dtype=None, name=None):  # noqa: A002
        x = ensure_tensor(x)
        ax = _norm_axis(axis)
        def fn(a):
            if dtype_arg and dtype is not None:
                a = a.astype(core.convert_dtype(dtype))
            elif opname == "sum" and jnp.issubdtype(a.dtype, jnp.bool_):
                a = a.astype(jnp.int32)
            return jfn(a, axis=ax, keepdims=keepdim)
        return apply_op(opname, fn, (x,), {}, differentiable=differentiable)
    op.__name__ = opname
    return _export(opname, op)


_reduction("sum", jnp.sum, dtype_arg=True)
_reduction("mean", jnp.mean, dtype_arg=True)
_reduction("prod", jnp.prod, dtype_arg=True)
_reduction("max", jnp.max)
_reduction("min", jnp.min)
_reduction("amax", jnp.amax)
_reduction("amin", jnp.amin)
_reduction("nansum", jnp.nansum, dtype_arg=True)
_reduction("nanmean", jnp.nanmean)
_reduction("all", jnp.all, differentiable=False)
_reduction("any", jnp.any, differentiable=False)
_reduction("logsumexp", jsp_special.logsumexp)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _norm_axis(axis)
    return apply_op("count_nonzero",
                    lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim),
                    (x,), {}, differentiable=False)
_export("count_nonzero", count_nonzero)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply_op("var", lambda a: jnp.var(a, axis=ax, ddof=ddof, keepdims=keepdim),
                    (x,), {})
_export("var", var)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = _norm_axis(axis)
    ddof = 1 if unbiased else 0
    return apply_op("std", lambda a: jnp.std(a, axis=ax, ddof=ddof, keepdims=keepdim),
                    (x,), {})
_export("std", std)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    x = ensure_tensor(x)
    ax = _norm_axis(axis)
    return apply_op("median", lambda a: jnp.median(a, axis=ax, keepdims=keepdim),
                    (x,), {})
_export("median", median)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    x = ensure_tensor(x)
    ax = _norm_axis(axis)
    qv = jnp.asarray(q)
    return apply_op("quantile",
                    lambda a: jnp.quantile(a, qv, axis=ax, keepdims=keepdim,
                                           method=interpolation),
                    (x,), {})
_export("quantile", quantile)


# ---------------------------------------------------------------------------
# scans & misc
# ---------------------------------------------------------------------------

def cumsum(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)
    ax = _norm_axis(axis)
    def fn(a):
        if ax is None:
            a = a.reshape(-1)
            return jnp.cumsum(a, dtype=core.convert_dtype(dtype))
        return jnp.cumsum(a, axis=ax, dtype=core.convert_dtype(dtype))
    return apply_op("cumsum", fn, (x,), {})
_export("cumsum", cumsum)


def cumprod(x, dim=None, dtype=None, name=None):
    x = ensure_tensor(x)
    return apply_op("cumprod",
                    lambda a: jnp.cumprod(a, axis=dim, dtype=core.convert_dtype(dtype)),
                    (x,), {})
_export("cumprod", cumprod)


def cummax(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    def fn(a):
        if axis is None:
            a2 = a.reshape(-1)
            vals = jax.lax.cummax(a2, axis=0)
            return vals
        return jax.lax.cummax(a, axis=axis)
    values = apply_op("cummax", fn, (x,), {})
    # indices pass (non-differentiable)
    def idx_fn(a):
        ax = 0 if axis is None else axis
        a2 = a.reshape(-1) if axis is None else a
        n = a2.shape[ax]
        iota = jax.lax.broadcasted_iota(jnp.int32, a2.shape, ax)
        vals = jax.lax.cummax(a2, axis=ax)
        isnew = a2 >= vals  # True where a new max is set
        idx = jax.lax.cummax(jnp.where(isnew, iota, -1), axis=ax)
        return idx.astype(core.convert_dtype(dtype))
    indices = apply_op("cummax_idx", idx_fn, (x,), {}, differentiable=False)
    return values, indices
_export("cummax", cummax)


def cummin(x, axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    def fn(a):
        a2 = a.reshape(-1) if axis is None else a
        return jax.lax.cummin(a2, axis=0 if axis is None else axis)
    values = apply_op("cummin", fn, (x,), {})
    def idx_fn(a):
        ax = 0 if axis is None else axis
        a2 = a.reshape(-1) if axis is None else a
        iota = jax.lax.broadcasted_iota(jnp.int32, a2.shape, ax)
        vals = jax.lax.cummin(a2, axis=ax)
        isnew = a2 <= vals
        idx = jax.lax.cummax(jnp.where(isnew, iota, -1), axis=ax)
        return idx.astype(core.convert_dtype(dtype))
    indices = apply_op("cummin_idx", idx_fn, (x,), {}, differentiable=False)
    return values, indices
_export("cummin", cummin)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)
    def fn(a):
        if axis is None:
            a2 = a.reshape(-1)
            return jax.lax.cumlogsumexp(a2, axis=0)
        return jax.lax.cumlogsumexp(a, axis=axis)
    return apply_op("logcumsumexp", fn, (x,), {})
_export("logcumsumexp", logcumsumexp)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = ensure_tensor(x)
    pre = prepend._data if isinstance(prepend, Tensor) else prepend
    app = append._data if isinstance(append, Tensor) else append
    return apply_op("diff",
                    lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app),
                    (x,), {})
_export("diff", diff)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    x = ensure_tensor(x)
    return apply_op("trace",
                    lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
                    (x,), {})
_export("trace", trace)


def kron(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("kron", jnp.kron, (x, y), {})
_export("kron", kron)


def cross(x, y, axis=9, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    ax = axis if axis != 9 else None
    def fn(a, b):
        if ax is None:
            # first axis with dim 3 (paddle semantics)
            for i, d in enumerate(a.shape):
                if d == 3:
                    return jnp.cross(a, b, axis=i)
            raise ValueError("cross: no axis with dimension 3")
        return jnp.cross(a, b, axis=ax)
    return apply_op("cross", fn, (x, y), {})
_export("cross", cross)


def inner(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("inner", jnp.inner, (x, y), {})
_export("inner", inner)


def outer(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    return apply_op("outer", lambda a, b: jnp.outer(a.reshape(-1), b.reshape(-1)),
                    (x, y), {})
_export("outer", outer)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    input, x, y = ensure_tensor(input), ensure_tensor(x), ensure_tensor(y)
    return apply_op("addmm",
                    lambda i, a, b: beta * i + alpha * (a @ b), (input, x, y), {})
_export("addmm", addmm)


def renorm(x, p, axis, max_norm, name=None):
    x = ensure_tensor(x)
    def fn(a):
        dims = tuple(i for i in range(a.ndim) if i != axis)
        norms = jnp.sum(jnp.abs(a) ** p, axis=dims, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return a * factor
    return apply_op("renorm", fn, (x,), {})
_export("renorm", renorm)


def histogram(input, bins=100, min=0, max=0, name=None):
    input = ensure_tensor(input)
    def fn(a):
        lo, hi = (float(min), float(max))
        if lo == 0 and hi == 0:
            lo, hi = jnp.min(a), jnp.max(a)
        h, _ = jnp.histogram(a.reshape(-1), bins=bins, range=(lo, hi))
        return h
    return apply_op("histogram", fn, (input,), {}, differentiable=False)
_export("histogram", histogram)


def bincount(x, weights=None, minlength=0, name=None):
    x = ensure_tensor(x)
    n = int(x.numpy().max()) + 1 if x.size else 0
    length = builtins.max(n, minlength)
    if weights is not None:
        w = ensure_tensor(weights)
        return apply_op("bincount",
                        lambda a, ww: jnp.bincount(a.reshape(-1), ww.reshape(-1),
                                                   length=length),
                        (x, w), {}, differentiable=False)
    return apply_op("bincount", lambda a: jnp.bincount(a.reshape(-1), length=length),
                    (x,), {}, differentiable=False)
_export("bincount", bincount)


def broadcast_shape(x_shape, y_shape):
    import numpy as np
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))
_export("broadcast_shape", broadcast_shape)


def increment(x, value=1.0, name=None):
    x = ensure_tensor(x)
    out = apply_op("increment", lambda a: a + value, (x,), {})
    x._replace_data(out._data)
    return x
_export("increment", increment)
