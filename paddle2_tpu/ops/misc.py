"""Remaining top-level API surface (reference python/paddle/__init__.py
__all__ diff): dtype aliases, small tensor utilities, rng-state shims."""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from .dispatch import apply_op, ensure_tensor

__all__ = ["dtype", "float8_e4m3fn", "float8_e5m2", "rank", "shape",
           "add_n", "reverse", "histogram_bin_edges", "is_complex",
           "is_integer", "is_floating_point", "get_cuda_rng_state",
           "set_cuda_rng_state", "set_printoptions",
           "disable_signal_handler", "CUDAPinnedPlace", "create_parameter",
           "check_shape", "reduce_as", "as_strided", "diagonal_scatter",
           "LazyGuard", "batch", "flops"]

# paddle.dtype: accepts "float32"/np dtypes; jnp's dtype object is the
# TPU-native datatype descriptor
dtype = jnp.dtype
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2


def rank(input, name=None) -> Tensor:
    """Number of dimensions as a 0-D tensor (tensor/attribute.py rank)."""
    return Tensor(jnp.asarray(ensure_tensor(input).ndim, jnp.int32))


def shape(input, name=None) -> Tensor:
    return Tensor(jnp.asarray(tuple(ensure_tensor(input).shape),
                              jnp.int32))


def add_n(inputs, name=None) -> Tensor:
    ts = tuple(ensure_tensor(t) for t in
               (inputs if isinstance(inputs, (list, tuple)) else [inputs]))
    return apply_op("add_n", lambda *xs: sum(xs[1:], xs[0]), ts, {})


def reverse(x, axis, name=None) -> Tensor:
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply_op("reverse", lambda a: jnp.flip(a, ax),
                    (ensure_tensor(x),), {})


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None) -> Tensor:
    arr = np.asarray(ensure_tensor(input).numpy())
    rng = None if (min == 0 and max == 0) else (min, max)
    return Tensor(jnp.asarray(np.histogram_bin_edges(arr, bins=bins,
                                                     range=rng)))


def is_complex(x) -> bool:
    return jnp.issubdtype(ensure_tensor(x)._data.dtype,
                          jnp.complexfloating)


def is_integer(x) -> bool:
    return jnp.issubdtype(ensure_tensor(x)._data.dtype, jnp.integer)


def is_floating_point(x) -> bool:
    return jnp.issubdtype(ensure_tensor(x)._data.dtype, jnp.floating)


def get_cuda_rng_state():
    """Accelerator RNG state (maps onto the framework key chain)."""
    from ..framework import random as fr
    return [fr.get_state()] if hasattr(fr, "get_state") else []


def set_cuda_rng_state(state):
    from ..framework import random as fr
    if state and hasattr(fr, "set_state"):
        fr.set_state(state[0])


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def disable_signal_handler():
    """No-op: the reference installs C++ crash handlers; PJRT has none."""


class CUDAPinnedPlace:
    """Place alias (host staging memory is PJRT-managed on TPU)."""

    def __repr__(self):
        return "CUDAPinnedPlace"


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """paddle.create_parameter (tensor/creation.py): standalone Parameter."""
    from ..nn.layer.layers import Layer
    holder = Layer()
    return holder.create_parameter(list(shape), attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


def check_shape(x):
    return tuple(ensure_tensor(x).shape)


def reduce_as(x, target, name=None) -> Tensor:
    """Sum-reduce x to target's (broadcast-compatible) shape."""
    xt, tt = ensure_tensor(x), ensure_tensor(target)
    tgt = tuple(tt.shape)

    def f(a):
        extra = a.ndim - len(tgt)
        if extra > 0:
            a = jnp.sum(a, axis=tuple(range(extra)))
        axes = tuple(i for i, (d, t) in enumerate(zip(a.shape, tgt))
                     if d != t and t == 1)
        if axes:
            a = jnp.sum(a, axis=axes, keepdims=True)
        return a
    return apply_op("reduce_as", f, (xt,), {})


def as_strided(x, shape, stride, offset=0, name=None) -> Tensor:
    """Strided view re-expressed as a gather (XLA arrays have no strides;
    the index matrix reproduces the reference's aliasing READ semantics —
    writes do not alias back)."""
    xt = ensure_tensor(x)
    shape = tuple(int(s) for s in shape)
    stride = tuple(int(s) for s in stride)
    idx = np.full(shape, int(offset), np.int64)
    for d, (s, st) in enumerate(zip(shape, stride)):
        ar = np.arange(s) * st
        idx += ar.reshape((1,) * d + (s,) + (1,) * (len(shape) - d - 1))

    def f(a):
        return a.reshape(-1)[jnp.asarray(idx)]
    return apply_op("as_strided", f, (xt,), {})


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None) -> Tensor:
    def f(a, b):
        n1, n2 = a.shape[axis1], a.shape[axis2]
        k = min(n1, n2 - offset) if offset >= 0 else min(n1 + offset, n2)
        i = jnp.arange(k) + (-offset if offset < 0 else 0)
        j = jnp.arange(k) + (offset if offset >= 0 else 0)
        idx = [slice(None)] * a.ndim
        idx[axis1], idx[axis2] = i, j
        # paddle.diagonal puts the diagonal dim LAST in y; numpy advanced
        # indexing separated by slices puts it FIRST in the set target
        b = jnp.moveaxis(b, -1, 0)
        return a.at[tuple(idx)].set(b.astype(a.dtype))
    return apply_op("diagonal_scatter", f,
                    (ensure_tensor(x), ensure_tensor(y)), {})


class LazyGuard:
    """lazy init guard (reference LazyGuard defers parameter
    materialization; this runtime materializes eagerly — the guard exists
    so reference scripts run, with identical results and eager memory)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def batch(reader, batch_size, drop_last=False):
    """Legacy reader combinator (paddle.batch)."""

    def gen():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return gen


def flops(net, input_size, custom_ops=None, print_detail=False) -> int:
    """paddle.flops (hapi/dynamic_flops.py): rough multiply-add count via
    forward hooks on Linear/Conv layers."""
    from .. import nn, zeros
    total = {"flops": 0}
    hooks = []

    def conv_hook(l, inputs, output):
        out_el = int(np.prod(output.shape[1:]))
        kernel = int(np.prod(l._kernel_size)) * (l._in_channels
                                                 // l._groups)
        total["flops"] += out_el * (2 * kernel - 1)

    def linear_hook(l, inputs, output):
        total["flops"] += 2 * int(np.prod(output.shape[1:])) \
            * int(l.weight.shape[0])

    for layer in net.sublayers(include_self=True):
        if isinstance(layer, nn.Conv2D):
            hooks.append(layer.register_forward_post_hook(conv_hook))
        elif isinstance(layer, nn.Linear):
            hooks.append(layer.register_forward_post_hook(linear_hook))
    try:
        net(zeros(list(input_size)))
    finally:
        for h in hooks:
            h.remove()
    return total["flops"]
