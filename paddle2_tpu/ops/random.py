"""Random ops over the global (or traced) PRNG (python/paddle/tensor/random.py).

Every call consumes a split of the framework key (framework/random.py); inside
jit.to_static traces the key is threaded through the compiled function so
randomness stays a function of inputs, not a baked constant.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import core
from ..framework import random as fr
from ..framework.tensor import Tensor
from .dispatch import ensure_tensor

__all__ = ["rand", "randn", "randint", "randint_like", "randperm", "uniform",
           "normal", "standard_normal", "poisson", "bernoulli", "multinomial",
           "uniform_", "normal_", "exponential_", "binomial", "standard_gamma",
           "log_normal", "cauchy_", "geometric_"]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().reshape(-1))
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                 for s in shape)


def _dt(dtype):
    d = core.convert_dtype(dtype)
    return d if d is not None else core.get_default_dtype()


def rand(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jax.random.uniform(fr.next_key(), _shape(shape), _dt(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    key = jax.random.PRNGKey(seed) if seed else fr.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype),
                                     minval=min, maxval=max))


def randn(shape, dtype=None, name=None) -> Tensor:
    return Tensor(jax.random.normal(fr.next_key(), _shape(shape), _dt(dtype)))


def standard_normal(shape, dtype=None, name=None) -> Tensor:
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None) -> Tensor:
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        eps = jax.random.normal(fr.next_key(), shp,
                                core.get_default_dtype())
        return Tensor(m + s * eps)
    shp = _shape(shape if shape is not None else [1])
    eps = jax.random.normal(fr.next_key(), shp, core.get_default_dtype())
    return Tensor(mean + std * eps)


def log_normal(mean=1.0, std=2.0, shape=None, name=None) -> Tensor:
    g = normal(mean, std, shape)
    return Tensor(jnp.exp(g._data))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None) -> Tensor:
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(fr.next_key(), _shape(shape), low, high,
                                     core.convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None) -> Tensor:
    x = ensure_tensor(x)
    if high is None:
        low, high = 0, low
    dt = core.convert_dtype(dtype) or x.dtype
    out = jax.random.randint(fr.next_key(), tuple(x.shape), low, high, jnp.int32)
    return Tensor(out.astype(dt))


def randperm(n, dtype="int64", name=None) -> Tensor:
    return Tensor(jax.random.permutation(fr.next_key(), n).astype(
        core.convert_dtype(dtype)))


def poisson(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jax.random.poisson(fr.next_key(), x._data).astype(x.dtype))


def bernoulli(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jax.random.bernoulli(fr.next_key(), x._data).astype(x.dtype))


def binomial(count, prob, name=None) -> Tensor:
    count, prob = ensure_tensor(count), ensure_tensor(prob)
    out = jax.random.binomial(fr.next_key(), count._data.astype(jnp.float32),
                              prob._data)
    return Tensor(out.astype(jnp.int32))


def standard_gamma(x, name=None) -> Tensor:
    x = ensure_tensor(x)
    return Tensor(jax.random.gamma(fr.next_key(), x._data).astype(x.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None) -> Tensor:
    x = ensure_tensor(x)
    def draw(row_p):
        logits = jnp.log(jnp.clip(row_p, 1e-30, None))
        return jax.random.choice(fr.next_key(), row_p.shape[-1],
                                 shape=(num_samples,),
                                 replace=replacement, p=row_p / row_p.sum())
    a = x._data
    if a.ndim == 1:
        return Tensor(draw(a).astype(jnp.int32))
    rows = [draw(a[i]) for i in range(a.shape[0])]
    return Tensor(jnp.stack(rows).astype(jnp.int32))


# in-place variants (tensor method patches)

def uniform_(x, min=-1.0, max=1.0, seed=0, name=None) -> Tensor:
    x = ensure_tensor(x)
    x._replace_data(jax.random.uniform(fr.next_key(), tuple(x.shape),
                                       x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
                                       else core.get_default_dtype(),
                                       minval=min, maxval=max))
    return x


def normal_(x, mean=0.0, std=1.0, name=None) -> Tensor:
    x = ensure_tensor(x)
    eps = jax.random.normal(fr.next_key(), tuple(x.shape), x.dtype)
    x._replace_data(mean + std * eps)
    return x


def exponential_(x, lam=1.0, name=None) -> Tensor:
    x = ensure_tensor(x)
    u = jax.random.exponential(fr.next_key(), tuple(x.shape), x.dtype)
    x._replace_data(u / lam)
    return x


def cauchy_(x, loc=0, scale=1, name=None) -> Tensor:
    x = ensure_tensor(x)
    u = jax.random.cauchy(fr.next_key(), tuple(x.shape), x.dtype)
    x._replace_data(loc + scale * u)
    return x


def geometric_(x, probs, name=None) -> Tensor:
    x = ensure_tensor(x)
    u = jax.random.uniform(fr.next_key(), tuple(x.shape))
    out = jnp.ceil(jnp.log1p(-u) / jnp.log1p(-probs))
    x._replace_data(out.astype(x.dtype))
    return x
