"""paddle.optimizer namespace (python/paddle/optimizer/ parity)."""

from . import lr  # noqa: F401
from .optimizer import Optimizer  # noqa: F401
from .optimizers import (  # noqa: F401
    SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, ASGD, Lamb, LBFGS, Lion,
    Momentum, NAdam, RAdam, RMSProp, Rprop,
)
