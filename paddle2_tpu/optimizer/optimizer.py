"""Optimizer base (python/paddle/optimizer/optimizer.py:127 parity).

Redesigned for XLA: each step() call runs ONE jitted pytree update over all
parameters (params, grads, states are flat lists → a single fused TPU kernel
per optimizer, the equivalent of the reference's fused/multi_tensor adam
kernels) instead of per-parameter kernel launches. The update rule itself is
a pure function `_update_one(param, grad, state, lr)` supplied by subclasses.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import core
from ..framework.tensor import Parameter, Tensor
from .lr import LRScheduler


def _clip_arrays(grad_clip, grads, need_clip_flags):
    """Gradient clipping over the clippable subset — shared by the
    generic and fused update builders (one owner, identical ops)."""
    if grad_clip is None:
        return grads
    clippable = [g for g, c in zip(grads, need_clip_flags) if c]
    clipped = grad_clip.apply_arrays(clippable)
    it = iter(clipped)
    return [next(it) if c else g
            for g, c in zip(grads, need_clip_flags)]


class Optimizer:
    _hyper: Dict[str, float] = {}

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False, **kwargs):
        if parameters is None:
            raise ValueError(
                "parameters is required in dygraph mode "
                "(pass model.parameters())")
        # param groups: list of Parameter or list of dicts {'params': [...]}
        self._param_groups: List[Dict[str, Any]] = []
        params_list = list(parameters)
        if params_list and isinstance(params_list[0], dict):
            for g in params_list:
                g = dict(g)
                g["params"] = list(g["params"])
                self._param_groups.append(g)
        else:
            self._param_groups.append({"params": params_list})
        self._lr = learning_rate
        self._weight_decay = self._wd_value(weight_decay)
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._states: Dict[int, Any] = {}
        self._step_count = 0
        self._jit_cache: Dict[Any, Any] = {}

    @staticmethod
    def _wd_value(weight_decay):
        """Returns (kind, coeff): kind is 'l2' or 'l1'."""
        if weight_decay is None:
            return ("l2", 0.0)
        if isinstance(weight_decay, (int, float)):
            return ("l2", float(weight_decay))
        coeff = float(getattr(weight_decay, "_coeff",
                              getattr(weight_decay, "coeff", 0.0)))
        kind = "l1" if type(weight_decay).__name__ == "L1Decay" else "l2"
        return (kind, coeff)

    # -- lr --------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return float(self._lr)

    def set_lr(self, value: float):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    def set_lr_scheduler(self, scheduler: LRScheduler):
        self._lr = scheduler

    @property
    def _learning_rate(self):
        return self._lr

    # -- state -----------------------------------------------------------
    def _init_state(self, p: Parameter):
        """Return the initial state pytree for one parameter (subclass)."""
        return ()

    def _ensure_state(self, p: Parameter):
        key = id(p)
        if key not in self._states:
            state = self._init_state(p)
            if self._multi_precision and p._data.dtype in (jnp.bfloat16,
                                                           jnp.float16):
                state = {"master": p._data.astype(jnp.float32),
                         "inner": state}
            self._states[key] = state
        return self._states[key]

    # -- the pure update -------------------------------------------------
    def _update_one(self, param, grad, state, lr, step):
        raise NotImplementedError

    def _decoupled_wd(self) -> bool:
        return False  # AdamW overrides

    def _use_fused_step(self) -> bool:
        """Opt-in Pallas fused-step routing: the explicit ``fused=``
        ctor kwarg wins, else FLAGS_fused_optimizer_step."""
        explicit = getattr(self, "_fused_step", None)
        if explicit is not None:
            return bool(explicit)
        from ..flags import flag_value
        return bool(flag_value("fused_optimizer_step"))

    def _fused_update_builder(self, need_clip_flags, decay_flags):
        """Subclasses with a Pallas one-pass kernel (AdamW, Momentum)
        return a drop-in `update` here; None falls back to the generic
        per-op chain. Any fused update MUST be bitwise equal to the
        generic path — it is a layout/fusion change, never a numerics
        change (bench --single-chip-speed gates this)."""
        return None

    def _fused_paramwise_builder(self, need_clip_flags, decay_flags,
                                 kernel):
        """ONE owner for the fused-update scaffolding every subclass
        shares: clipping, multi-precision master unwrap/re-wrap, the
        explicit f32 grad cast, and the per-tensor fallback to
        `_apply_one`. ``kernel(work, g, inner, lr, step, wd_eff)``
        returns ``(new_work, new_inner)`` or None when this tensor is
        unsupported (then the generic chain serves it, still bitwise
        by construction). l1 decay falls back wholesale — the kernels
        implement the l2 fold only."""
        wd_kind, wd = self._weight_decay
        if wd and wd_kind != "l2":
            return None
        grad_clip = self._grad_clip
        multi_prec = self._multi_precision
        apply_one = self._apply_one

        def update(params, grads, states, lr, step):
            grads = _clip_arrays(grad_clip, grads, need_clip_flags)
            new_params, new_states = [], []
            for p, g, s, decay in zip(params, grads, states,
                                      decay_flags):
                master = None
                inner = s
                if multi_prec and isinstance(s, dict) and "master" in s:
                    master, inner = s["master"], s["inner"]
                work = master if master is not None else p
                g_eff = g.astype(jnp.float32) if master is not None \
                    else g
                res = kernel(work, g_eff, inner, lr, step,
                             wd if (wd and decay) else 0.0)
                if res is None:
                    np_, ns_ = apply_one(p, g, s, lr, step, decay)
                    new_params.append(np_)
                    new_states.append(ns_)
                    continue
                np_, ns_ = res
                if master is not None:
                    new_params.append(np_.astype(p.dtype))
                    new_states.append({"master": np_, "inner": ns_})
                else:
                    new_params.append(np_)
                    new_states.append(ns_)
            return new_params, new_states
        return update

    def _apply_one(self, p, g, s, lr, step, decay):
        """The per-parameter update body (weight decay + _update_one +
        multi-precision master handling) shared by the generic update
        and, as the per-tensor fallback, the fused paths."""
        wd_kind, wd = self._weight_decay
        decoupled = self._decoupled_wd()
        master = None
        inner = s
        if self._multi_precision and isinstance(s, dict) \
                and "master" in s:
            master, inner = s["master"], s["inner"]
            work_p = master
            g = g.astype(jnp.float32)
        else:
            work_p = p
        if wd and decay and not decoupled:
            reg = jnp.sign(work_p) if wd_kind == "l1" else work_p
            g = g + wd * reg
        np_, ns_ = self._update_one(work_p, g, inner, lr, step)
        if wd and decay and decoupled:
            reg = jnp.sign(work_p) if wd_kind == "l1" else work_p
            np_ = np_ - lr * wd * reg
        if master is not None:
            return np_.astype(p.dtype), {"master": np_, "inner": ns_}
        return np_, ns_

    def _build_update(self, need_clip_flags, decay_flags):
        """The pure fused update `(params, grads, states, lr, step) ->
        (new_params, new_states)` over flat lists — the TPU analog of the
        reference's multi_tensor/fused optimizer kernels
        (paddle/phi/kernels/fusion/fused_adam_kernel.cu): one traced
        program updates every parameter. Used jitted-with-donation by
        step() and inlined by jit.train_step's single-executable path.

        With the fused-step opt-in, subclasses may swap the per-param
        op chain for a one-pass Pallas kernel (bitwise-identical by
        contract); everything else — clipping, decay flags, master
        weights — is unchanged."""
        if self._use_fused_step():
            fused = self._fused_update_builder(need_clip_flags,
                                               decay_flags)
            if fused is not None:
                return fused
        apply_one = self._apply_one
        grad_clip = self._grad_clip

        def update(params, grads, states, lr, step):
            grads = _clip_arrays(grad_clip, grads, need_clip_flags)
            new_params, new_states = [], []
            for p, g, s, decay in zip(params, grads, states, decay_flags):
                np_, ns_ = apply_one(p, g, s, lr, step, decay)
                new_params.append(np_)
                new_states.append(ns_)
            return new_params, new_states
        return update

    def _make_update_fn(self, need_clip_flags, decay_flags, donate: bool):
        # donate the OPTIMIZER STATES (master weights + moments, ~3x model
        # size in f32): XLA aliases their update in place. Parameter arrays
        # are NOT donated on this eager path — Tensor.detach()/views may
        # alias them across steps (jit.train_step, an explicit opt-in API,
        # donates params too). Grads are never donated — clear_grad owns
        # their lifetime.
        return jax.jit(self._build_update(need_clip_flags, decay_flags),
                       donate_argnums=(2,) if donate else ())

    # -- step ------------------------------------------------------------
    @core.no_grad
    def step(self):
        self._step_count += 1
        all_params: List[Parameter] = []
        for group in self._param_groups:
            for p in group["params"]:
                if p is not None and p.trainable and p.grad is not None:
                    all_params.append(p)
        if not all_params:
            return
        params = [p._data for p in all_params]
        grads = [p.grad._data for p in all_params]
        states = [self._ensure_state(p) for p in all_params]
        need_clip = tuple(bool(getattr(p, "need_clip", True))
                          for p in all_params)
        decay_flags = tuple(not getattr(p, "no_weight_decay", False)
                            for p in all_params)
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        step = jnp.asarray(self._step_count, jnp.int32)
        from ..flags import flag_value
        donate = bool(flag_value("donate_optimizer_buffers"))
        cache_key = (len(params), need_clip, decay_flags, donate,
                     self._use_fused_step(),
                     tuple(p.shape + (str(p.dtype),) for p in params))
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            fn = self._make_update_fn(need_clip, decay_flags, donate)
            self._jit_cache[cache_key] = fn
        new_params, new_states = fn(params, grads, states, lr, step)
        for p, np_, ns_ in zip(all_params, new_params, new_states):
            p._replace_data(np_)
            self._states[id(p)] = ns_

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero: bool = False):
        for group in self._param_groups:
            for p in group["params"]:
                p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    # -- checkpointing ---------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"_step_count": self._step_count}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        idx = 0
        for group in self._param_groups:
            for p in group["params"]:
                key = p.name or f"param_{idx}"
                if id(p) in self._states:
                    # snapshot COPIES: live state buffers are donated to the
                    # next fused update, which would invalidate shared refs
                    out[key] = jax.tree_util.tree_map(
                        lambda a: Tensor(jnp.array(a, copy=True))
                        if isinstance(a, jnp.ndarray) else a,
                        self._states[id(p)])
                idx += 1
        return out

    def set_state_dict(self, state_dict: Dict[str, Any]):
        self._step_count = int(state_dict.get("_step_count", 0))
        if isinstance(self._lr, LRScheduler) and "LR_Scheduler" in state_dict:
            self._lr.set_state_dict(state_dict["LR_Scheduler"])
        idx = 0
        for group in self._param_groups:
            for p in group["params"]:
                key = p.name or f"param_{idx}"
                if key in state_dict:
                    # copy on load: the restored arrays become donation
                    # candidates, which must not delete the caller's
                    # data. The numpy branch must copy EXPLICITLY too —
                    # jnp.asarray may alias a suitably-aligned host
                    # buffer on the CPU backend, and a donated alias of
                    # a rollback snapshot frees the snapshot itself (a
                    # second restore of the same step would then read
                    # freed memory)
                    self._states[id(p)] = jax.tree_util.tree_map(
                        lambda a: jnp.array(a._data, copy=True)
                        if isinstance(a, Tensor)
                        else jnp.array(a, copy=True)
                        if isinstance(a, np.ndarray) else a,
                        state_dict[key])
                else:
                    # the snapshot predates this param's lazily-created
                    # state (e.g. taken before the first step): restore
                    # means UNINITIALIZED, not "keep whatever moments
                    # accumulated since" — stale moments make a
                    # rolled-back Adam step diverge bitwise from the
                    # original, which the SDC fingerprint vote would
                    # then misread as corruption
                    self._states.pop(id(p), None)
                idx += 1

    def _parameter_list(self):
        out = []
        for g in self._param_groups:
            out.extend(g["params"])
        return out
