"""Concrete optimizers (python/paddle/optimizer/{sgd,momentum,adam,adamw,
lamb,...}.py parity). Each defines only the pure per-parameter update rule;
the base class fuses all parameters into one jitted TPU kernel."""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
           "Adadelta", "RMSProp", "Lamb", "Lion", "NAdam", "RAdam", "LBFGS"]


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _update_one(self, param, grad, state, lr, step):
        return param - lr * grad, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None, fused=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov
        # fused=True/False overrides FLAGS_fused_optimizer_step
        self._fused_step = fused

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(
            p._data, dtype=jnp.float32 if self._multi_precision else None)}

    def _update_one(self, param, grad, state, lr, step):
        v = self._momentum * state["velocity"] + grad
        if self._nesterov:
            new_p = param - lr * (grad + self._momentum * v)
        else:
            new_p = param - lr * v
        return new_p, {"velocity": v}

    def _fused_update_builder(self, need_clip_flags, decay_flags):
        """One-pass Pallas momentum (kernels/pallas_fused.py
        fused_momentum_step), bitwise vs the generic chain on f32
        state; l1 decay and unsupported dtypes fall back per tensor.
        Scaffolding lives in the base `_fused_paramwise_builder`."""
        from ..kernels import pallas_fused as pf
        mom, nesterov = self._momentum, self._nesterov

        def kernel(work, g, inner, lr, step, wd_eff):
            if not (isinstance(inner, dict)
                    and set(inner) == {"velocity"}
                    and inner["velocity"].dtype == jnp.float32
                    and pf.adamw_step_supported(work, g)):
                return None
            np_, nv = pf.fused_momentum_step(
                work, g, inner["velocity"], lr, momentum=mom,
                nesterov=nesterov, weight_decay=wd_eff)
            return np_, {"velocity": nv}
        return self._fused_paramwise_builder(need_clip_flags,
                                             decay_flags, kernel)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None, amsgrad=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._amsgrad = amsgrad

    def _init_state(self, p):
        dt = jnp.float32 if p._data.dtype in (jnp.bfloat16, jnp.float16) \
            else p._data.dtype
        s = {"m": jnp.zeros(p._data.shape, dt),
             "v": jnp.zeros(p._data.shape, dt)}
        if self._amsgrad:
            s["vmax"] = jnp.zeros(p._data.shape, dt)
        return s

    def _update_one(self, param, grad, state, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        t = step.astype(jnp.float32)
        m = b1 * state["m"] + (1 - b1) * grad
        v = b2 * state["v"] + (1 - b2) * jnp.square(grad)
        mhat = m / (1 - b1 ** t)
        if self._amsgrad:
            vmax = jnp.maximum(state["vmax"], v)
            vhat = vmax / (1 - b2 ** t)
            new_state = {"m": m, "v": v, "vmax": vmax}
        else:
            vhat = v / (1 - b2 ** t)
            new_state = {"m": m, "v": v}
        new_p = param - lr * mhat / (jnp.sqrt(vhat) + eps)
        return new_p, new_state


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None,
                 amsgrad=False, fused=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name, amsgrad)
        self._apply_decay_fn = apply_decay_param_fun
        # fused=True/False overrides FLAGS_fused_optimizer_step: route
        # the per-param update through the one-pass Pallas kernel
        # (bitwise vs the generic chain — bench-gated)
        self._fused_step = fused
        if apply_decay_param_fun is not None:
            # mark params excluded from decay so the fused update skips them
            for g in self._param_groups:
                for p in g["params"]:
                    if not apply_decay_param_fun(p.name):
                        p.no_weight_decay = True

    def _decoupled_wd(self):
        return True

    def _fused_update_builder(self, need_clip_flags, decay_flags):
        """One-pass Pallas AdamW (kernels/pallas_fused.py
        fused_adamw_step): reads (p, g, m, v), writes (p, m, v) with
        in-place aliases — no staging copies — in the EXACT eager op
        order, so params and moments stay bitwise. amsgrad / l1 decay
        configs and non-f32 math fall back (per tensor) to the
        generic chain. Scaffolding lives in the base
        `_fused_paramwise_builder`."""
        if self._amsgrad:
            return None
        from ..kernels import pallas_fused as pf
        b1, b2, eps = self._beta1, self._beta2, self._eps

        def kernel(work, g, inner, lr, step, wd_eff):
            if not (isinstance(inner, dict)
                    and set(inner) == {"m", "v"}
                    and inner["m"].dtype == jnp.float32
                    and pf.adamw_step_supported(work, g)):
                return None
            np_, nm, nv = pf.fused_adamw_step(
                work, g, inner["m"], inner["v"], lr, step,
                beta1=b1, beta2=b2, eps=eps, weight_decay=wd_eff)
            return np_, {"m": nm, "v": nv}
        return self._fused_paramwise_builder(need_clip_flags,
                                             decay_flags, kernel)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_state(self, p):
        return {"m": jnp.zeros_like(p._data),
                "u": jnp.zeros_like(p._data)}

    def _update_one(self, param, grad, state, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        t = step.astype(jnp.float32)
        m = b1 * state["m"] + (1 - b1) * grad
        u = jnp.maximum(b2 * state["u"], jnp.abs(grad))
        new_p = param - lr / (1 - b1 ** t) * m / (u + eps)
        return new_p, {"m": m, "u": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full_like(p._data, self._init_acc)}

    def _update_one(self, param, grad, state, lr, step):
        moment = state["moment"] + jnp.square(grad)
        new_p = param - lr * grad / (jnp.sqrt(moment) + self._eps)
        return new_p, {"moment": moment}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._eps, self._rho = epsilon, rho

    def _init_state(self, p):
        return {"avg_sq_grad": jnp.zeros_like(p._data),
                "avg_sq_update": jnp.zeros_like(p._data)}

    def _update_one(self, param, grad, state, lr, step):
        rho, eps = self._rho, self._eps
        asg = rho * state["avg_sq_grad"] + (1 - rho) * jnp.square(grad)
        update = (jnp.sqrt(state["avg_sq_update"] + eps)
                  / jnp.sqrt(asg + eps)) * grad
        asu = rho * state["avg_sq_update"] + (1 - rho) * jnp.square(update)
        return param - lr * update, {"avg_sq_grad": asg, "avg_sq_update": asu}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, p):
        s = {"mean_square": jnp.zeros_like(p._data),
             "moment": jnp.zeros_like(p._data)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(p._data)
        return s

    def _update_one(self, param, grad, state, lr, step):
        rho, eps = self._rho, self._eps
        ms = rho * state["mean_square"] + (1 - rho) * jnp.square(grad)
        if self._centered:
            mg = rho * state["mean_grad"] + (1 - rho) * grad
            denom = jnp.sqrt(ms - jnp.square(mg) + eps)
            new_state = {"mean_square": ms, "mean_grad": mg}
        else:
            denom = jnp.sqrt(ms + eps)
            new_state = {"mean_square": ms}
        mom = self._momentum * state["moment"] + lr * grad / denom
        new_state["moment"] = mom
        return param - mom, new_state


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        dt = jnp.float32
        return {"m": jnp.zeros(p._data.shape, dt),
                "v": jnp.zeros(p._data.shape, dt),
                "wd": jnp.asarray(
                    0.0 if (self._exclude_fn is not None
                            and self._exclude_fn(p)) else self._lamb_wd,
                    dt)}

    def _update_one(self, param, grad, state, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        t = step.astype(jnp.float32)
        m = b1 * state["m"] + (1 - b1) * grad
        v = b2 * state["v"] + (1 - b2) * jnp.square(grad)
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + eps) + state["wd"] * param
        w_norm = jnp.linalg.norm(param.astype(jnp.float32))
        r_norm = jnp.linalg.norm(r.astype(jnp.float32))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return param - lr * trust * r, {"m": m, "v": v, "wd": state["wd"]}


class Lion(Optimizer):
    """Lion (EvoLved sign momentum) — bf16-friendly, half the state of Adam."""

    def __init__(self, learning_rate=1e-4, beta1=0.9, beta2=0.99,
                 parameters=None, weight_decay=0.0, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2 = beta1, beta2

    def _decoupled_wd(self):
        return True

    def _init_state(self, p):
        return {"m": jnp.zeros_like(p._data)}

    def _update_one(self, param, grad, state, lr, step):
        b1, b2 = self._beta1, self._beta2
        update = jnp.sign(b1 * state["m"] + (1 - b1) * grad)
        m = b2 * state["m"] + (1 - b2) * grad
        return param - lr * update, {"m": m}


class NAdam(Adam):
    def _update_one(self, param, grad, state, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        t = step.astype(jnp.float32)
        m = b1 * state["m"] + (1 - b1) * grad
        v = b2 * state["v"] + (1 - b2) * jnp.square(grad)
        mhat = (b1 * m / (1 - b1 ** (t + 1))
                + (1 - b1) * grad / (1 - b1 ** t))
        vhat = v / (1 - b2 ** t)
        return param - lr * mhat / (jnp.sqrt(vhat) + eps), {"m": m, "v": v}


class RAdam(Adam):
    def _update_one(self, param, grad, state, lr, step):
        b1, b2, eps = self._beta1, self._beta2, self._eps
        t = step.astype(jnp.float32)
        m = b1 * state["m"] + (1 - b1) * grad
        v = b2 * state["v"] + (1 - b2) * jnp.square(grad)
        mhat = m / (1 - b1 ** t)
        rho_inf = 2.0 / (1 - b2) - 1
        rho_t = rho_inf - 2 * t * b2 ** t / (1 - b2 ** t)
        def rect_update():
            r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                         / ((rho_inf - 4) * (rho_inf - 2) * rho_t))
            vhat = jnp.sqrt(v / (1 - b2 ** t))
            return param - lr * r * mhat / (vhat + eps)
        new_p = jnp.where(rho_t > 5.0, rect_update(), param - lr * mhat)
        return new_p, {"m": m, "v": v}


class LBFGS(Optimizer):
    """L-BFGS (python/paddle/optimizer/lbfgs.py parity, strong-Wolfe-free
    variant with fixed step fallback). Runs eagerly: the two-loop recursion
    over a deque of (s, y) pairs is host-side control flow by nature."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._max_iter = max_iter
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history_size = history_size
        self._line_search_fn = line_search_fn
        self._hist_s: list = []
        self._hist_y: list = []
        self._prev_flat = None
        self._prev_grad = None

    def _flat(self, arrs):
        return jnp.concatenate([a.reshape(-1).astype(jnp.float32)
                                for a in arrs])

    def step(self, closure=None):
        if closure is not None:
            with jax.disable_jit(False):
                loss = closure()
        params = [p for g in self._param_groups for p in g["params"]
                  if p.trainable and p.grad is not None]
        if not params:
            return
        flat = self._flat([p._data for p in params])
        grad = self._flat([p.grad._data for p in params])
        if self._prev_flat is not None:
            s = flat - self._prev_flat
            y = grad - self._prev_grad
            if float(jnp.dot(s, y)) > 1e-10:
                self._hist_s.append(s)
                self._hist_y.append(y)
                if len(self._hist_s) > self._history_size:
                    self._hist_s.pop(0)
                    self._hist_y.pop(0)
        q = grad
        alphas = []
        for s, y in zip(reversed(self._hist_s), reversed(self._hist_y)):
            rho = 1.0 / jnp.dot(y, s)
            a = rho * jnp.dot(s, q)
            q = q - a * y
            alphas.append((a, rho, s, y))
        if self._hist_s:
            s, y = self._hist_s[-1], self._hist_y[-1]
            q = q * (jnp.dot(s, y) / jnp.dot(y, y))
        for a, rho, s, y in reversed(alphas):
            b = rho * jnp.dot(y, q)
            q = q + (a - b) * s
        direction = -q
        lr = self.get_lr()
        new_flat = flat + lr * direction
        self._prev_flat = flat
        self._prev_grad = grad
        offset = 0
        for p in params:
            n = int(np_prod(p.shape))
            p._replace_data(new_flat[offset:offset + n]
                            .reshape(p._data.shape).astype(p._data.dtype))
            offset += n
        self._step_count += 1


def np_prod(shape):
    out = 1
    for s in shape:
        out *= s
    return out


class ASGD(Optimizer):
    """Stochastic Average Gradient (reference optimizer/asgd.py:41,
    asgd_kernel.cc): a rotating buffer of the last ``batch_num``
    gradients whose running sum drives the step:
        i = m % n;  d += grad - y_i;  y_i = grad;
        param -= lr * d / min(m+1, n)
    (the lambda*x term is the base class's weight_decay)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        if batch_num < 1:
            raise ValueError("batch_num must be >= 1")
        self._n = int(batch_num)

    def _init_state(self, p):
        return {"d": jnp.zeros_like(p._data),
                "y": jnp.zeros((self._n,) + tuple(p._data.shape),
                               p._data.dtype)}

    def _update_one(self, param, grad, state, lr, step):
        m = step.astype(jnp.int32) - 1            # 0-based update count
        i = jnp.mod(m, self._n)
        y_i = state["y"][i]
        d = state["d"] - y_i + grad
        y = state["y"].at[i].set(grad)
        denom = jnp.minimum(m + 1, self._n).astype(jnp.float32)
        new_p = param - lr * d / denom
        return new_p, {"d": d, "y": y}


class Rprop(Optimizer):
    """Resilient backprop (reference optimizer/rprop.py, rprop_kernel.cc):
    per-weight step sizes grow by eta+ while the gradient keeps its sign,
    shrink by eta- on a sign flip (where the step is skipped), clamped to
    learning_rate_range; the update is sign(grad) * step."""

    def __init__(self, learning_rate=0.001,
                 learning_rate_range=(1e-5, 50.0), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lr0 = float(learning_rate)
        self._lr_min, self._lr_max = (float(learning_rate_range[0]),
                                      float(learning_rate_range[1]))
        self._eta_neg, self._eta_pos = float(etas[0]), float(etas[1])

    def _init_state(self, p):
        return {"prev": jnp.zeros_like(p._data),
                "lr": jnp.full_like(p._data, self._lr0)}

    def _update_one(self, param, grad, state, lr, step):
        product = grad * state["prev"]
        eta = jnp.where(product > 0, self._eta_pos,
                        jnp.where(product < 0, self._eta_neg, 1.0))
        grad = jnp.where(product < 0, 0.0, grad)   # skip on sign flip
        lr_elt = jnp.clip(state["lr"] * eta, self._lr_min, self._lr_max)
        new_p = param - jnp.sign(grad) * lr_elt
        return new_p, {"prev": grad, "lr": lr_elt}
