"""paddle.profiler (reference python/paddle/profiler/profiler.py:358
Profiler, :120 make_scheduler, utils.py RecordEvent, timer.py ips
benchmark).

TPU-native design: the heavyweight device timeline comes from jax.profiler
(xprof/TensorBoard trace of XLA execution — the counterpart of the
reference's CUPTI tracer), while host-side op records + RecordEvent spans
are collected in-process and exported as a chrome://tracing JSON, the same
artifact the reference's chrometracing_logger.cc writes.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Dict, List, Optional, Sequence, Union

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "SortedKeys", "SummaryView", "benchmark", "merge_traces"]


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1       # accepted for API parity; maps to the TPU device stream
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    GPUTotal = 3


class SummaryView(Enum):
    OverView = 0
    OpView = 1


def make_scheduler(closed: int = 0, ready: int = 0, record: int = 1,
                   repeat: int = 0, skip_first: int = 0):
    """profiler.py:120 parity: step -> ProfilerState machine."""
    cycle = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        if repeat and step >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = step % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


class _Collector:
    """In-process event sink (host spans + op records)."""

    def __init__(self):
        self.events: List[Dict] = []
        self.lock = threading.Lock()
        self.enabled = False
        self.t0 = time.perf_counter()

    def add(self, name: str, cat: str, start: float, dur: float,
            args: Optional[dict] = None):
        if not self.enabled:
            return
        with self.lock:
            self.events.append({
                "name": name, "cat": cat, "ph": "X",
                "ts": (start - self.t0) * 1e6, "dur": dur * 1e6,
                "pid": os.getpid(), "tid": threading.get_ident(),
                "args": args or {}})


_collector = _Collector()


# True while a jax.profiler device trace is running (set by
# Profiler._sync_device_trace): RecordEvent mirrors its spans into the
# xprof timeline only when there IS one to land in
_device_trace_active = False


class RecordEvent:
    """User-annotated span (reference utils.py RecordEvent / the
    nvtx-range analog). Usable as context manager or begin()/end().

    One annotation, three correlated timelines:

    * the host chrome trace (always, when a Profiler is recording);
    * the xprof device timeline — when a ``jax.profiler`` trace is
      active the span also opens a ``TraceAnnotation``, so user marks
      line up against the XLA execution rows in TensorBoard;
    * the flight-recorder ring — ``user_span`` events carry the name
      and duration into crash dumps, so a post-mortem can say WHICH
      phase of the step the gang died in.
    """

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._start: Optional[float] = None
        self._annotation = None

    def begin(self):
        if _device_trace_active:
            try:
                import jax
                self._annotation = jax.profiler.TraceAnnotation(
                    self.name)
                self._annotation.__enter__()
            except Exception:
                self._annotation = None
        from ..distributed.fault_tolerance import flight_recorder
        flight_recorder.record("user_span_begin", name=self.name)
        self._start = time.perf_counter()

    def end(self):
        if self._start is not None:
            dur = time.perf_counter() - self._start
            _collector.add(self.name, "user", self._start, dur)
            self._start = None
            if self._annotation is not None:
                try:
                    self._annotation.__exit__(None, None, None)
                except Exception:
                    pass
                self._annotation = None
            from ..distributed.fault_tolerance import flight_recorder
            flight_recorder.record("user_span_end", name=self.name,
                                   dur_s=round(dur, 6))

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    """Returns an on_trace_ready callback writing chrome://tracing JSON
    (chrometracing_logger.cc artifact parity)."""

    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_time_{int(time.time())}"
                                      ".paddle_trace.json")
        prof._export_path = path
        with open(path, "w") as f:
            json.dump({"traceEvents": prof._events,
                       "displayTimeUnit": "ms"}, f)

    return handler


def load_profiler_result(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def merge_traces(dir_name: str, output_path: Optional[str] = None,
                 align: bool = True) -> dict:
    """Merge the per-process ``*.paddle_trace.json`` files under
    ``dir_name`` into ONE chrome://tracing timeline with a process lane
    per rank (reference ``tools/CrossStackProfiler/`` multi-node trace
    merger). Worker/rank identity comes from the filename prefix the
    per-rank ``export_chrome_tracing(worker_name=...)`` wrote.

    ``align=True`` shifts each rank's events so its earliest timestamp
    is 0 — per-process monotonic clocks share no epoch, so lanes are
    comparable in DURATION and STRUCTURE, not absolute offset (noted in
    the merged metadata). Returns the merged trace dict and writes it to
    ``output_path`` (default ``dir_name/merged.paddle_trace.json``)."""
    files = sorted(f for f in os.listdir(dir_name)
                   if f.endswith(".paddle_trace.json")
                   and not f.startswith("merged"))
    if not files:
        raise ValueError(f"no *.paddle_trace.json traces in {dir_name!r}")
    merged: List[Dict] = []
    for lane, fname in enumerate(files):
        worker = fname.split("_time_")[0] if "_time_" in fname \
            else fname.rsplit(".paddle_trace.json", 1)[0]
        with open(os.path.join(dir_name, fname)) as f:
            events = json.load(f).get("traceEvents", [])
        spans = [e for e in events if e.get("ph") != "M"]
        t0 = min((e["ts"] for e in spans if "ts" in e), default=0.0) \
            if align else 0.0
        merged.append({"name": "process_name", "ph": "M", "pid": lane,
                       "args": {"name": worker}})
        merged.append({"name": "process_sort_index", "ph": "M",
                       "pid": lane, "args": {"sort_index": lane}})
        for e in spans:
            e = dict(e)
            e["pid"] = lane
            if align and "ts" in e:
                e["ts"] = e["ts"] - t0
            merged.append(e)
    out = {"traceEvents": merged, "displayTimeUnit": "ms",
           "metadata": {"merged_from": files,
                        "aligned_per_rank": bool(align),
                        "note": "per-rank monotonic clocks share no "
                                "epoch; lanes are start-aligned"}}
    path = output_path or os.path.join(dir_name,
                                       "merged.paddle_trace.json")
    with open(path, "w") as f:
        json.dump(out, f)
    return out


class Profiler:
    """profiler.py:358 parity: scheduler-driven start/stop/step with
    summary and chrome-trace export; device timeline via jax.profiler."""

    def __init__(self, targets: Optional[Sequence] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 record_shapes: bool = False, profile_memory: bool = False,
                 timer_only: bool = False, emit_nvtx: bool = False,
                 custom_device_types=None, with_flops: bool = False):
        if callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, ready=0,
                                             record=hi - lo, repeat=1)
        else:
            self._scheduler = lambda step: ProfilerState.RECORD
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self.step_num = 0
        self._state = ProfilerState.CLOSED
        self._events: List[Dict] = []
        self._step_starts: List[float] = []
        self._export_path: Optional[str] = None
        self._jax_trace_dir: Optional[str] = None

    # -- lifecycle -------------------------------------------------------
    def start(self):
        self._state = self._scheduler(self.step_num)
        _collector.enabled = self._state in (ProfilerState.RECORD,
                                             ProfilerState.RECORD_AND_RETURN)
        _collector.events = []
        self._step_starts = [time.perf_counter()]
        self._sync_device_trace()
        return self

    def _recording(self) -> bool:
        return self._state in (ProfilerState.RECORD,
                               ProfilerState.RECORD_AND_RETURN)

    def _sync_device_trace(self):
        """xprof tracing follows the scheduler: device capture runs only
        inside RECORD windows (skip_first/closed steps stay untraced).
        The module-level ``_device_trace_active`` flag tracks the trace
        state so RecordEvent spans mirror into the xprof timeline."""
        global _device_trace_active
        if self._timer_only:
            return
        import jax
        want = self._recording()
        have = self._jax_trace_dir is not None
        if want and not have:
            try:
                self._jax_trace_dir = os.environ.get(
                    "PADDLE2_TPU_XPROF_DIR", "/tmp/paddle2_tpu_xprof")
                jax.profiler.start_trace(self._jax_trace_dir)
                _device_trace_active = True
            except Exception:
                self._jax_trace_dir = None
        elif not want and have:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_trace_dir = None
            _device_trace_active = False

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._step_starts:
            _collector.add(f"ProfileStep#{self.step_num}", "step",
                           self._step_starts[-1], now - self._step_starts[-1],
                           {"num_samples": num_samples})
        self._step_starts.append(now)
        self.step_num += 1
        self._state = self._scheduler(self.step_num)
        _collector.enabled = self._state in (ProfilerState.RECORD,
                                             ProfilerState.RECORD_AND_RETURN)
        self._sync_device_trace()

    def stop(self):
        global _device_trace_active
        if self._jax_trace_dir is not None:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_trace_dir = None
            _device_trace_active = False
        self._events = list(_collector.events)
        _collector.enabled = False
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- reporting -------------------------------------------------------
    def summary(self, sorted_by=SortedKeys.CPUTotal, op_detail: bool = True,
                thread_sep: bool = False, time_unit: str = "ms"):
        """Aggregated per-name table (reference profiler summary).
        ``sorted_by`` picks the ordering column (``SortedKeys.CPUTotal``
        / ``CPUAvg`` / ``CPUMax``; ``GPUTotal`` aliases to total — the
        device stream is the TPU timeline here, same mapping as
        ``ProfilerTarget.GPU``) and ``time_unit`` scales the duration
        columns (``"s" | "ms" | "us" | "ns"``, reflected in the row
        keys: ``total_ms`` / ``avg_ms`` / ``max_ms`` for the default)."""
        try:
            scale = {"s": 1e6, "ms": 1e3, "us": 1.0,
                     "ns": 1e-3}[time_unit]          # events store us
        except KeyError:
            raise ValueError(
                f"time_unit must be one of 's', 'ms', 'us', 'ns'; got "
                f"{time_unit!r}")
        ndigits = {"s": 6, "ms": 3, "us": 1, "ns": 0}[time_unit]
        agg: Dict[str, List[float]] = {}
        for e in self._events:
            agg.setdefault(e["name"], []).append(e["dur"] / scale)
        sort_col = {SortedKeys.CPUTotal: sum,
                    SortedKeys.GPUTotal: sum,
                    SortedKeys.CPUAvg: lambda d: sum(d) / len(d),
                    SortedKeys.CPUMax: max}.get(sorted_by, sum)
        rows = []
        for name, durs in sorted(agg.items(),
                                 key=lambda kv: -sort_col(kv[1])):
            rows.append({"name": name, "calls": len(durs),
                         f"total_{time_unit}": round(sum(durs), ndigits),
                         f"avg_{time_unit}": round(sum(durs) / len(durs),
                                                   ndigits),
                         f"max_{time_unit}": round(max(durs), ndigits)})
        return rows

    @property
    def events(self):
        return self._events


class benchmark:
    """timer.py ips benchmark parity: throughput meter (samples/s)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._t0 = None
        self._steps = 0
        self._samples = 0

    def begin(self):
        self.reset()
        self._t0 = time.perf_counter()

    def step(self, num_samples: int = 1):
        if self._t0 is None:
            self.begin()
        self._steps += 1
        self._samples += num_samples

    def end(self) -> dict:
        dt = time.perf_counter() - (self._t0 or time.perf_counter())
        return {"steps": self._steps, "elapsed_s": round(dt, 4),
                "ips": round(self._samples / dt, 2) if dt > 0 else 0.0,
                "step_per_sec": round(self._steps / dt, 2) if dt > 0
                else 0.0}


def export_protobuf(dir_name: str, worker_name: Optional[str] = None):
    """profiler/profiler.py export_protobuf: scheduler callback writing
    the collected trace. The reference's .pb feeds VisualDL; the
    portable binary container here is a length-prefixed pickle of the
    same event records (chrome-trace JSON remains the interchange
    format — export_chrome_tracing)."""
    import os
    import pickle

    def handle(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"worker_{os.getpid()}"
        events = getattr(prof, "_events", [])
        payload = pickle.dumps({"version": 1, "events": [
            dict(e) if isinstance(e, dict) else e for e in events]})
        with open(os.path.join(dir_name, name + ".pb"), "wb") as f:
            f.write(len(payload).to_bytes(8, "little"))
            f.write(payload)
    return handle


__all__.append("export_protobuf")
