"""paddle.quantization (reference python/paddle/quantization/: QuantConfig,
QAT, PTQ, quanters).

TPU-native scope: int8 MXU matmuls exist but the dominant use is QAT
simulation + export; this implements per-tensor absmax fake quantization
(straight-through estimator) as differentiable jnp ops, a QAT pass that
swaps Linear/Conv2D for quantized twins, and a PTQ pass with absmax
observers.
"""

from __future__ import annotations

from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op
from .. import nn

__all__ = ["QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMaxObserver",
           "FakeQuanterChannelWiseAbsMaxObserver", "AbsmaxObserver",
           "ChannelWiseAbsMaxObserver", "QuantedInferenceLinear",
           "WeightOnlyLinear", "WeightOnlyLMHead",
           "weight_only_quantize", "quantize_lm_head",
           "channel_absmax", "quant_aware", "fake_quant"]


def channel_absmax(w, axis: int = 1):
    """Per-channel absmax along ``axis`` — the ONE reduction the
    channel-wise observers, the weight-only packers, and the
    training-time quantized lm_head share (kernels/pallas_matmul.py
    owns the primitive, so the scales agree bitwise everywhere).
    Accepts a Tensor or array; returns a jnp f32 array."""
    from ..kernels.pallas_matmul import channel_absmax as _ca
    arr = w._data if isinstance(w, Tensor) else jnp.asarray(w)
    return _ca(arr, axis)


def _fake_quant_fn(x, scale, bits, axis=None):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    if axis is not None:
        shape = [1] * x.ndim
        shape[axis] = -1
        s = s.reshape(shape)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    deq = q * s / qmax
    # straight-through estimator: identity gradient inside the clip range
    return x + jax.lax.stop_gradient(deq - x)


def fake_quant(x: Tensor, scale, bits: int = 8, quant_axis=None) -> Tensor:
    """Per-tensor (scalar scale) or per-channel (1-D scale + quant_axis)
    fake quantization with STE gradients."""
    from ..ops.dispatch import ensure_tensor
    t = ensure_tensor(x)
    s = jnp.asarray(scale._data if isinstance(scale, Tensor) else scale,
                    jnp.float32)
    return apply_op("fake_quant",
                    lambda a: _fake_quant_fn(a, s, bits, quant_axis),
                    (t,), {})


class AbsmaxObserver(nn.Layer):
    """PTQ observer: tracks running absmax (observer/abs_max.py parity).

    State lives in registered BUFFERS, so the moving average (a) stays
    on device — no per-forward host sync (round-3 review), and (b)
    records under ``jit.to_static`` tracing: buffer mutations thread
    through the compiled program as extra outputs, exactly like
    BatchNorm running stats (r4 verdict #8)."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        super().__init__()
        self.quant_bits = quant_bits
        self.moving_rate = moving_rate
        # non-persistable: pre-r5 checkpoints have no observer keys, and
        # load_state_dict would refuse them otherwise
        self.register_buffer("_absmax", Tensor(jnp.zeros((), jnp.float32)),
                             persistable=False)
        self.register_buffer("_seen", Tensor(jnp.zeros((), jnp.float32)),
                             persistable=False)
        # frozen-ness is a BUFFER, not a python flag alone: a compiled
        # program traced before freeze() must stop updating afterwards
        # without retracing
        self.register_buffer("_frozen_buf",
                             Tensor(jnp.zeros((), jnp.float32)),
                             persistable=False)
        self._frozen = False

    def freeze(self):
        """Stop scale updates (PTQ.convert 'freeze' semantics)."""
        self._frozen = True
        if hasattr(self, "_frozen_buf"):   # pre-r5 pickled instances
            self._frozen_buf._replace_data(jnp.ones((), jnp.float32))

    def forward(self, x: Tensor) -> Tensor:
        # record until frozen, in train AND eval (reference observer
        # semantics — the standard PTQ recipe calibrates under eval()).
        # Call freeze() / PTQ.convert() before jit.save: exporting an
        # UNFROZEN observer bakes the scale update into the serving
        # program, making its output drift with input statistics.
        if self._frozen and not isinstance(x._data, jax.core.Tracer):
            return x
        cur = jnp.max(jnp.abs(x._data)).astype(jnp.float32)
        prev, seen = self._absmax._data, self._seen._data
        fb = getattr(self, "_frozen_buf", None)
        frozen = fb._data > 0 if fb is not None else jnp.asarray(False)
        new = jnp.where(seen > 0,
                        self.moving_rate * prev
                        + (1 - self.moving_rate) * cur, cur)
        self._absmax._replace_data(jnp.where(frozen, prev, new))
        self._seen._replace_data(
            jnp.where(frozen, seen, jnp.ones((), jnp.float32)))
        return x

    def raw_scale(self):
        """Device-resident scale (jnp scalar) — the QAT fake-quant path
        consumes this so an eager training step never blocks on D2H."""
        return jnp.where(self._seen._data > 0, self._absmax._data, 1.0)

    def scale(self) -> float:
        return float(self.raw_scale())       # one sync at read time


class ChannelWiseAbsMaxObserver(nn.Layer):
    """Per-channel PTQ observer (observer/abs_max_weight.py parity):
    tracks absmax along every channel of `quant_axis`.

    Buffer-backed and fully on device like :class:`AbsmaxObserver` — the
    per-forward reduction is a jnp op (no ``.numpy()`` host sync), and
    calibration records under tracing. ``channels`` (the extent of
    ``quant_axis``) sizes the buffer at construction; if omitted it is
    created lazily on the first EAGER forward — a first call under
    tracing would lose the update, so that case warns."""

    def __init__(self, quant_bits: int = 8, quant_axis: int = -1,
                 moving_rate: float = 0.9, channels: Optional[int] = None):
        super().__init__()
        self.quant_bits = quant_bits
        self.quant_axis = quant_axis
        self.moving_rate = moving_rate
        self._frozen = False
        if channels is not None:
            self._make_buffers(channels)

    def _make_buffers(self, channels: int):
        self.register_buffer(
            "_absmax", Tensor(jnp.zeros((channels,), jnp.float32)),
            persistable=False)
        self.register_buffer("_seen", Tensor(jnp.zeros((), jnp.float32)),
                             persistable=False)
        self.register_buffer("_frozen_buf",
                             Tensor(jnp.zeros((), jnp.float32)),
                             persistable=False)

    def freeze(self):
        self._frozen = True
        if hasattr(self, "_frozen_buf"):
            self._frozen_buf._replace_data(jnp.ones((), jnp.float32))

    def forward(self, x: Tensor) -> Tensor:
        # records in train AND eval until frozen (AbsmaxObserver.forward)
        if self._frozen and not isinstance(x._data, jax.core.Tracer):
            return x
        axis = self.quant_axis % x.ndim
        if not hasattr(self, "_absmax"):
            if isinstance(x._data, jax.core.Tracer):
                import warnings
                warnings.warn(
                    "ChannelWiseAbsMaxObserver: first forward is inside "
                    "a traced program but the channel buffer does not "
                    "exist yet, so this update cannot be recorded. Pass "
                    "channels= at construction or run one eager forward "
                    "first.", RuntimeWarning, stacklevel=2)
                return x
            self._make_buffers(int(x.shape[axis]))
        from ..kernels.pallas_matmul import channel_absmax as _ca
        cur = _ca(x._data, axis)
        prev, seen = self._absmax._data, self._seen._data
        fb = getattr(self, "_frozen_buf", None)
        frozen = fb._data > 0 if fb is not None else jnp.asarray(False)
        new = jnp.where(seen > 0,
                        self.moving_rate * prev
                        + (1 - self.moving_rate) * cur, cur)
        self._absmax._replace_data(jnp.where(frozen, prev, new))
        self._seen._replace_data(
            jnp.where(frozen, seen, jnp.ones((), jnp.float32)))
        return x

    def raw_scale(self):
        """Device-resident per-channel scales (jnp array)."""
        if not hasattr(self, "_absmax"):
            return jnp.ones((), jnp.float32)
        return jnp.where(self._seen._data > 0, self._absmax._data, 1.0)

    def scale(self):
        import numpy as np
        if not hasattr(self, "_absmax"):
            return 1.0
        return np.asarray(self.raw_scale())  # one sync at read time


class FakeQuanterWithAbsMaxObserver(nn.Layer):
    """QAT quanter (quanters/abs_max.py parity): observes absmax online
    and fake-quantizes with STE."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9,
                 dtype="float32", name=None):
        super().__init__()
        self.observer = AbsmaxObserver(quant_bits, moving_rate)
        self.quant_bits = quant_bits

    def forward(self, x: Tensor) -> Tensor:
        self.observer(x)
        return fake_quant(x, self.observer.raw_scale(), self.quant_bits)


class FakeQuanterChannelWiseAbsMaxObserver(nn.Layer):
    """Per-channel QAT weight quanter (quanters/abs_max.py channel-wise
    variant): one scale per output channel — the accuracy saver for
    weight quantization."""

    def __init__(self, quant_bits: int = 8, quant_axis: int = 0,
                 moving_rate: float = 0.9, dtype="float32", name=None,
                 channels: Optional[int] = None):
        # reference default quant_axis=0 (the OUTPUT channel of a Conv2D
        # weight [out,in,kh,kw]); Linear weights [in,out] need axis 1 —
        # _QuantedWrapper passes the right axis + channel count per
        # layer type
        super().__init__()
        self.observer = ChannelWiseAbsMaxObserver(quant_bits, quant_axis,
                                                  moving_rate,
                                                  channels=channels)
        self.quant_bits = quant_bits
        self.quant_axis = quant_axis

    def forward(self, x: Tensor) -> Tensor:
        self.observer(x)
        axis = self.quant_axis % x.ndim
        return fake_quant(x, self.observer.raw_scale(), self.quant_bits,
                          quant_axis=axis)


class QuantConfig:
    """config.py QuantConfig parity (activation/weight quanter factories)."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._type_map: Dict[type, type] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        self._type_map[layer_type] = (activation, weight)

    def quanter_for(self, layer):
        act, w = self.activation, self.weight
        for t, (a2, w2) in self._type_map.items():
            if isinstance(layer, t):
                act, w = a2 or act, w2 or w
        return act, w


class _QuantedWrapper(nn.Layer):
    """Wraps a Linear/Conv2D: fake-quant activations in, weights inline."""

    def __init__(self, inner: nn.Layer, act_quanter, w_quanter):
        super().__init__()
        self.inner = inner
        self.act_quanter = act_quanter() if isinstance(act_quanter, type) \
            else act_quanter
        if isinstance(w_quanter, type):
            if issubclass(w_quanter, FakeQuanterChannelWiseAbsMaxObserver):
                # output channel: axis 1 for Linear [in,out], 0 for Conv2D
                axis = 1 if isinstance(inner, nn.Linear) else 0
                channels = int(inner.weight.shape[axis])
                w_quanter = w_quanter(quant_axis=axis, channels=channels)
            else:
                w_quanter = w_quanter()
        self.w_quanter = w_quanter

    def forward(self, x):
        from ..nn import functional as F
        if self.act_quanter is not None:
            x = self.act_quanter(x)
        if self.w_quanter is None:
            return self.inner(x)
        fq = self.w_quanter(self.inner.weight)  # grads flow to the weight
        if isinstance(self.inner, nn.Linear):
            return F.linear(x, fq, self.inner.bias)
        if isinstance(self.inner, nn.Conv2D):
            c = self.inner
            return F.conv2d(x, fq, c.bias, stride=c._stride,
                            padding=c._padding, dilation=c._dilation,
                            groups=c._groups)
        return self.inner(x)


_QUANTABLE = (nn.Linear, nn.Conv2D)


def _swap(model: nn.Layer, config: QuantConfig) -> nn.Layer:
    for name, child in list(model.named_children()):
        if isinstance(child, _QUANTABLE):
            act, w = config.quanter_for(child)
            if act is None and w is None:
                act = w = FakeQuanterWithAbsMaxObserver
            model.add_sublayer(name, _QuantedWrapper(child, act, w))
        else:
            _swap(child, config)
    return model


class QAT:
    """qat.py QAT parity: quantize() swaps quantable layers in place."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: nn.Layer, inplace: bool = True) -> nn.Layer:
        return _swap(model, self.config)


class QuantedInferenceLinear(nn.Layer):
    """INT8 inference Linear: weights stored int8 with per-channel f32
    scales; the matmul runs on int8 operands with int32 accumulation
    (the TPU MXU int8 path — 2x the bf16 rate on v5e), then dequantizes.
    Produced by PTQ.convert() (reference int8 export,
    static/quantization post-training pipeline)."""

    def __init__(self, weight_int8, w_scale, bias, act_scale,
                 quant_bits: int = 8):
        super().__init__()
        # buffers, not plain attributes: state_dict()/jit.save must
        # carry the int8 weights and scales
        self.register_buffer("weight_int8",
                             Tensor(jnp.asarray(weight_int8, jnp.int8)))
        self.register_buffer("w_scale",
                             Tensor(jnp.asarray(w_scale, jnp.float32)))
        self.register_buffer(
            "bias", None if bias is None else Tensor(jnp.asarray(bias)))
        self.act_scale = float(act_scale)
        self.qmax = float(2 ** (quant_bits - 1) - 1)

    def forward(self, x):
        from ..ops.dispatch import ensure_tensor
        t = ensure_tensor(x)

        def fn(a):
            s_in = max(self.act_scale, 1e-8)
            q_in = jnp.clip(jnp.round(a / s_in * self.qmax),
                            -self.qmax, self.qmax).astype(jnp.int8)
            acc = jax.lax.dot_general(
                q_in, self.weight_int8._data,
                (((a.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            deq = acc.astype(jnp.float32) * (
                s_in / self.qmax) * (self.w_scale._data / self.qmax)
            if self.bias is not None:
                deq = deq + self.bias._data
            return deq.astype(a.dtype)

        return apply_op("quanted_linear", fn, (t,), {})


class WeightOnlyLinear(nn.Layer):
    """INT8 *weight-only* Linear: weights stored int8 with per-output-
    channel f32 absmax scales, dequantized on the fly; activations stay
    floating point. The LLM-serving recipe (distinct from
    :class:`QuantedInferenceLinear`'s full-int8 path): decode steps are
    weight-bandwidth-bound, so halving the weight bytes buys up to 2x
    decode throughput with none of the activation-quantization accuracy
    risk. Produced by :func:`weight_only_quantize`."""

    def __init__(self, weight_int8, w_scale, bias, quant_bits: int = 8):
        super().__init__()
        # buffers: state_dict()/jit.save carry the int8 payload, and the
        # serving decode program receives them as runtime arguments
        self.register_buffer("weight_int8",
                             Tensor(jnp.asarray(weight_int8, jnp.int8)))
        self.register_buffer("w_scale",
                             Tensor(jnp.asarray(w_scale, jnp.float32)))
        self.register_buffer(
            "bias", None if bias is None else Tensor(jnp.asarray(bias)))
        self.qmax = float(2 ** (quant_bits - 1) - 1)
        self.quant_bits = quant_bits

    def forward(self, x):
        from ..ops.dispatch import ensure_tensor
        from ..kernels.pallas_matmul import int8_weight_only_matmul
        t = ensure_tensor(x)
        quant_bits = getattr(self, "quant_bits", None)
        if quant_bits is None:
            # pre-r10 pickled instances carry only qmax; the bit width
            # is exactly recoverable from qmax = 2**(bits-1) - 1 —
            # assuming 8 would mis-scale any non-8-bit payload by
            # qmax_true/127
            import math
            quant_bits = int(round(math.log2(self.qmax + 1))) + 1

        def fn(a):
            # kernels/pallas_matmul dispatch: the Pallas weight-only
            # kernel on TPU for aligned shapes (int8 tiles streamed —
            # half the weight HBM bytes), the equivalent XLA dequant
            # matmul elsewhere
            out = int8_weight_only_matmul(
                a, self.weight_int8._data, self.w_scale._data,
                bias=None if self.bias is None else self.bias._data,
                quant_bits=quant_bits)
            return out.astype(a.dtype)

        return apply_op("weight_only_linear", fn, (t,), {})


class WeightOnlyLMHead(nn.Layer):
    """INT8 weight-only LM head: the ``[hidden, vocab]`` head read of a
    GPT-style model, quantized per VOCAB channel. Shared-embedding
    aware by construction: it stores its OWN int8 payload of
    ``wte.weight.T`` (or the untied ``lm_head.weight``), so the
    embedding lookup keeps the fp table while the logits matmul — the
    biggest single projection in the model — streams int8. Installed by
    :func:`quantize_lm_head`; ``GPTForCausalLM._head`` routes through
    it when present."""

    def __init__(self, weight_int8, w_scale, quant_bits: int = 8):
        super().__init__()
        self.register_buffer("weight_int8",
                             Tensor(jnp.asarray(weight_int8, jnp.int8)))
        self.register_buffer("w_scale",
                             Tensor(jnp.asarray(w_scale, jnp.float32)))
        self.quant_bits = quant_bits

    def forward(self, x):
        from ..ops.dispatch import ensure_tensor
        from ..kernels.pallas_matmul import int8_weight_only_matmul
        t = ensure_tensor(x)

        def fn(a):
            out = int8_weight_only_matmul(
                a, self.weight_int8._data, self.w_scale._data,
                quant_bits=self.quant_bits)
            return out.astype(a.dtype)

        return apply_op("weight_only_lm_head", fn, (t,), {})


def _pack_weight_only(w_arr, quant_bits: int):
    """One observation of a static weight through the channel-wise
    observer (the shared calibration path), frozen, then packed int8 +
    f32 scales. Returns (w_int8, scale) numpy arrays."""
    import numpy as np
    out_ch = int(w_arr.shape[1])
    obs = ChannelWiseAbsMaxObserver(quant_bits=quant_bits,
                                    quant_axis=1, channels=out_ch)
    obs(w_arr if isinstance(w_arr, Tensor) else Tensor(jnp.asarray(w_arr)))
    obs.freeze()
    scale = np.maximum(np.asarray(obs.scale(), np.float32), 1e-8)
    qmax = 2 ** (quant_bits - 1) - 1
    w = np.asarray(
        w_arr.numpy() if isinstance(w_arr, Tensor) else w_arr,
        np.float32)
    w_int8 = np.clip(np.round(w / scale * qmax),
                     -qmax, qmax).astype(np.int8)
    return w_int8, scale


def quantize_lm_head(model: nn.Layer, quant_bits: int = 8) -> nn.Layer:
    """Quantize a causal-LM head to int8 weight-only, SHARED-EMBEDDING
    aware: with tied embeddings the packed payload is ``wte.weight.T``
    — the fp embedding table keeps serving the lookup — and with an
    untied head it is ``lm_head.weight``. Installs a
    :class:`WeightOnlyLMHead` sublayer the model's ``_head`` dispatch
    prefers; serving (``weight_only_int8``) and the training-time
    ``quantized_lm_head`` config share this one entry point (same
    observer, same scales — the fake-quant training forward equals
    this payload's dequantized product)."""
    cfg = getattr(model, "cfg", None)
    tied = bool(getattr(cfg, "tie_word_embeddings", False))
    if tied:
        w = model.gpt.wte.weight.T
    elif hasattr(model, "lm_head"):
        w = model.lm_head.weight
    else:
        raise ValueError(
            "quantize_lm_head: model has neither tied embeddings nor "
            "an lm_head Linear")
    w_int8, scale = _pack_weight_only(w, quant_bits)
    model.add_sublayer("_wo_head", WeightOnlyLMHead(
        w_int8, scale, quant_bits=quant_bits))
    return model


def weight_only_quantize(model: nn.Layer, quant_bits: int = 8,
                         include_lm_head: bool = False) -> nn.Layer:
    """Swap every ``nn.Linear`` under ``model`` (recursively, in place)
    for a :class:`WeightOnlyLinear`. Scales come from a frozen
    :class:`ChannelWiseAbsMaxObserver` pass over the weight (one
    observation — weights are static at serving time), per OUTPUT
    channel (axis 1 of the ``[in, out]`` Linear weight). Call it on the
    projection-bearing submodules only (e.g. each transformer block) to
    keep embeddings and the tied LM head in floating point — or pass
    ``include_lm_head=True`` on a causal-LM root to ALSO quantize the
    head through :func:`quantize_lm_head` (shared-embedding aware: the
    embedding lookup stays fp)."""
    if include_lm_head:
        # pack the head FIRST (the untied lm_head Linear must be read
        # as a head, not swept up by the generic swap below — _head
        # prefers the installed payload either way)
        quantize_lm_head(model, quant_bits=quant_bits)
    for name, child in list(model.named_children()):
        if include_lm_head and name in ("lm_head", "_wo_head"):
            continue
        if isinstance(child, nn.Linear):
            w_int8, scale = _pack_weight_only(child.weight, quant_bits)
            bias = None if child.bias is None else child.bias.numpy()
            model.add_sublayer(name, WeightOnlyLinear(
                w_int8, scale, bias, quant_bits=quant_bits))
        elif not isinstance(child, (WeightOnlyLMHead,)):
            weight_only_quantize(child, quant_bits=quant_bits)
    return model


class PTQ(QAT):
    """ptq.py PTQ parity: same swap with observers; convert() freezes the
    observed scales into INT8 inference layers (per-channel weights,
    per-tensor activations)."""

    def convert(self, model: nn.Layer, inplace: bool = True) -> nn.Layer:
        if not inplace:
            import copy
            model = copy.deepcopy(model)
        return self._convert_in_place(model)

    def _convert_in_place(self, model: nn.Layer) -> nn.Layer:
        for name, child in list(model.named_children()):
            if isinstance(child, _QuantedWrapper) \
                    and isinstance(child.inner, nn.Linear):
                import numpy as np
                w = np.asarray(child.inner.weight.numpy(), np.float32)
                w_scale = np.maximum(np.abs(w).max(axis=0), 1e-8)  # per out
                qmax = 2 ** 7 - 1
                w_int8 = np.clip(np.round(w / w_scale * qmax),
                                 -qmax, qmax).astype(np.int8)
                act_scale = 1.0
                if child.act_quanter is not None and hasattr(
                        child.act_quanter, "observer"):
                    act_scale = float(child.act_quanter.observer.scale())
                bias = None if child.inner.bias is None else \
                    np.asarray(child.inner.bias.numpy())
                model.add_sublayer(name, QuantedInferenceLinear(
                    w_int8, w_scale, bias, act_scale))
            elif isinstance(child, _QuantedWrapper):
                # Conv2D (and other quantables): int8 conv lowering is
                # not implemented — FREEZE the observed scales so the
                # simulated-quant forward stops drifting at inference
                for q in (child.act_quanter, child.w_quanter):
                    obs = getattr(q, "observer", None)
                    if obs is not None:
                        obs.freeze()
            else:
                self._convert_in_place(child)
        return model


def quant_aware(model: nn.Layer, config: Optional[QuantConfig] = None):
    return QAT(config).quantize(model)


class BaseObserver(nn.Layer):
    """quantization/base_observer.py: the observer protocol — watch
    tensors in forward, produce a scale. AbsmaxObserver/
    ChannelWiseAbsMaxObserver are the built-in implementations."""

    def forward(self, x):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def cal_thresholds(self):
        pass


class BaseQuanter(nn.Layer):
    """quantization/base_quanter.py: the quanter protocol — fake-quant
    in forward (FakeQuanterWithAbsMaxObserver is the built-in)."""

    def forward(self, x):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None


def quanter(name):
    """quantization/factory.py quanter decorator: register a Quanter
    class under ``name`` so QuantConfig can refer to it by string."""
    def decorator(cls):
        _QUANTER_REGISTRY[name] = cls
        cls.__quanter_name__ = name
        return cls
    return decorator


_QUANTER_REGISTRY = {}

__all__ += ["BaseObserver", "BaseQuanter", "quanter"]
