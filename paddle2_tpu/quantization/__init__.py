"""paddle.quantization (reference python/paddle/quantization/: QuantConfig,
QAT, PTQ, quanters).

TPU-native scope: int8 MXU matmuls exist but the dominant use is QAT
simulation + export; this implements per-tensor absmax fake quantization
(straight-through estimator) as differentiable jnp ops, a QAT pass that
swaps Linear/Conv2D for quantized twins, and a PTQ pass with absmax
observers.
"""

from __future__ import annotations

from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op
from .. import nn

__all__ = ["QuantConfig", "QAT", "PTQ", "FakeQuanterWithAbsMaxObserver",
           "AbsmaxObserver", "quant_aware", "fake_quant"]


def _fake_quant_fn(x, scale, bits):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    deq = q * s / qmax
    # straight-through estimator: identity gradient inside the clip range
    return x + jax.lax.stop_gradient(deq - x)


def fake_quant(x: Tensor, scale, bits: int = 8) -> Tensor:
    from ..ops.dispatch import ensure_tensor
    t = ensure_tensor(x)
    s = jnp.asarray(float(scale) if not isinstance(scale, Tensor)
                    else scale._data)
    return apply_op("fake_quant",
                    lambda a: _fake_quant_fn(a, s, bits), (t,), {})


class AbsmaxObserver(nn.Layer):
    """PTQ observer: tracks running absmax (observer/abs_max.py parity)."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        super().__init__()
        self.quant_bits = quant_bits
        self.moving_rate = moving_rate
        self._absmax = 0.0
        self._seen = False

    def forward(self, x: Tensor) -> Tensor:
        import numpy as np
        cur = float(np.abs(np.asarray(x.numpy())).max()) if not \
            isinstance(x._data, jax.core.Tracer) else None
        if cur is not None:
            if self._seen:
                self._absmax = (self.moving_rate * self._absmax
                                + (1 - self.moving_rate) * cur)
            else:
                self._absmax = cur
                self._seen = True
        return x

    def scale(self) -> float:
        return self._absmax if self._seen else 1.0


class FakeQuanterWithAbsMaxObserver(nn.Layer):
    """QAT quanter (quanters/abs_max.py parity): observes absmax online
    and fake-quantizes with STE."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9,
                 dtype="float32", name=None):
        super().__init__()
        self.observer = AbsmaxObserver(quant_bits, moving_rate)
        self.quant_bits = quant_bits

    def forward(self, x: Tensor) -> Tensor:
        self.observer(x)
        return fake_quant(x, self.observer.scale(), self.quant_bits)


class QuantConfig:
    """config.py QuantConfig parity (activation/weight quanter factories)."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._type_map: Dict[type, type] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        self._type_map[layer_type] = (activation, weight)

    def quanter_for(self, layer):
        act, w = self.activation, self.weight
        for t, (a2, w2) in self._type_map.items():
            if isinstance(layer, t):
                act, w = a2 or act, w2 or w
        return act, w


class _QuantedWrapper(nn.Layer):
    """Wraps a Linear/Conv2D: fake-quant activations in, weights inline."""

    def __init__(self, inner: nn.Layer, act_quanter, w_quanter):
        super().__init__()
        self.inner = inner
        self.act_quanter = act_quanter() if isinstance(act_quanter, type) \
            else act_quanter
        self.w_quanter = w_quanter() if isinstance(w_quanter, type) \
            else w_quanter

    def forward(self, x):
        from ..nn import functional as F
        if self.act_quanter is not None:
            x = self.act_quanter(x)
        if self.w_quanter is None:
            return self.inner(x)
        fq = self.w_quanter(self.inner.weight)  # grads flow to the weight
        if isinstance(self.inner, nn.Linear):
            return F.linear(x, fq, self.inner.bias)
        if isinstance(self.inner, nn.Conv2D):
            c = self.inner
            return F.conv2d(x, fq, c.bias, stride=c._stride,
                            padding=c._padding, dilation=c._dilation,
                            groups=c._groups)
        return self.inner(x)


_QUANTABLE = (nn.Linear, nn.Conv2D)


def _swap(model: nn.Layer, config: QuantConfig) -> nn.Layer:
    for name, child in list(model.named_children()):
        if isinstance(child, _QUANTABLE):
            act, w = config.quanter_for(child)
            if act is None and w is None:
                act = w = FakeQuanterWithAbsMaxObserver
            model.add_sublayer(name, _QuantedWrapper(child, act, w))
        else:
            _swap(child, config)
    return model


class QAT:
    """qat.py QAT parity: quantize() swaps quantable layers in place."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: nn.Layer, inplace: bool = True) -> nn.Layer:
        return _swap(model, self.config)


class PTQ(QAT):
    """ptq.py PTQ parity: same swap with pure observers; convert() freezes
    observed scales into the fake-quant path."""

    def convert(self, model: nn.Layer, inplace: bool = True) -> nn.Layer:
        return model


def quant_aware(model: nn.Layer, config: Optional[QuantConfig] = None):
    return QAT(config).quantize(model)
