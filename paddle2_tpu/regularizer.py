"""paddle.regularizer parity: L1Decay/L2Decay markers consumed by optimizers."""


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
