"""paddle2_tpu.serving — production LLM inference serving.

The millions-of-users path on top of the single-request
``paddle.inference`` surface (ROADMAP item 2): continuous batching
(Orca, Yu et al. OSDI'22), a paged KV cache with a Pallas
paged-attention decode kernel (vLLM PagedAttention, Kwon et al.
SOSP'23), prefill/decode disaggregation, opt-in int8 weight-only
quantization of the projection matmuls, and a deterministic
discrete-event serving bench driven by the PR 7 XLA cost model.

Entry points:

* :class:`ServingEngine` (``engine.py``) — wraps a ``jit.save``'d GPT
  artifact (or a live model) behind ``submit()``/``step()``;
  ``inference.Config.enable_continuous_batching()`` routes here.
* :func:`paged_attention_decode` (``paged_attention.py``) — the
  decode kernel; ``paged_attention_reference`` is its proven-bitwise
  dense twin.
* :class:`ContinuousBatchingScheduler` (``scheduler.py``) —
  admit/evict per decode step with bucketed batch shapes.
* :func:`simulate` (``simulate.py``) — the cost x rate
  discrete-event driver ``bench.py --serving`` gates on.
* :mod:`reliability` (PR 11) — the serving robustness plane: typed
  failure semantics (:class:`RequestRejected` /
  :class:`DeadlineExceeded` / :class:`EngineFailedError`), bounded
  admission with priority shedding (:class:`ReliabilityConfig`),
  engine-failure recovery from host token logs, the
  :class:`EngineFailoverRouter`, and zero-drop weight hot-swap
  (:class:`HotSwapController`) — gated by
  ``bench.py --serving-reliability``.
* Fleet-global KV (ISSUE 16) — the HBM -> host -> peer-DCN prefix
  ladder: :class:`HostKVTier` (CRC-verified host-DRAM spill tier),
  :class:`FleetKVRegistry` (peer fetch over DCN, priced against
  re-prefill by the PR 14 LinkModel), prefix-affinity routing and
  KV migration instead of re-prefill on failover, audited
  cross-tier by :func:`audit_kv_ledger` — gated by
  ``bench.py --fleet-kv``.
"""

from .block_cache import (BlockAllocator, BlockTable, PagedKVCache,
                          PrefixCache, HostKVTier, audit_kv_ledger,
                          blocks_for_tokens, GARBAGE_BLOCK)
from .block_cache import OutOfBlocksError, BlockFreeError
from .paged_attention import (paged_attention_decode,
                              paged_attention_reference,
                              paged_attention_split_reference,
                              gathered_dense_kv)
from .spec import SpeculativeConfig, ngram_draft, accept_drafts
from .reliability import (ServingError, RequestRejected, QueueFullError,
                          PromptTooLongError, DeadlineExceeded,
                          EngineFailedError, WeightSwapError,
                          ReliabilityConfig, SLOConfig,
                          HotSwapController)
from .scheduler import (Request, Sequence, SeqState,
                        ContinuousBatchingScheduler, SchedulerConfig)
from .engine import ServingEngine, EngineConfig
from .simulate import (ServingSimReport, simulate_serving,
                       simulate_predictor_baseline, poisson_trace,
                       diurnal_poisson_trace,
                       EngineFailoverRouter, RouterSimReport,
                       simulate_router, FleetKVRegistry)

__all__ = [
    "BlockAllocator", "BlockTable", "PagedKVCache", "PrefixCache",
    "HostKVTier", "audit_kv_ledger", "blocks_for_tokens",
    "GARBAGE_BLOCK", "OutOfBlocksError", "BlockFreeError",
    "paged_attention_decode", "paged_attention_reference",
    "paged_attention_split_reference", "gathered_dense_kv",
    "SpeculativeConfig", "ngram_draft", "accept_drafts",
    "ServingError", "RequestRejected", "QueueFullError",
    "PromptTooLongError", "DeadlineExceeded", "EngineFailedError",
    "WeightSwapError", "ReliabilityConfig", "SLOConfig",
    "HotSwapController",
    "Request", "Sequence", "SeqState", "ContinuousBatchingScheduler",
    "SchedulerConfig",
    "ServingEngine", "EngineConfig",
    "ServingSimReport", "simulate_serving", "simulate_predictor_baseline",
    "poisson_trace", "diurnal_poisson_trace",
    "EngineFailoverRouter", "RouterSimReport", "simulate_router",
    "FleetKVRegistry",
]
