"""Paged KV cache: fixed-size blocks + per-sequence block tables.

The vLLM PagedAttention memory model (Kwon et al. SOSP'23) adapted to
the TPU serving engine: the KV cache for ALL sequences lives in one
pool of fixed-size blocks per layer, and each sequence owns an ordered
list of block ids (its *block table*). Appending a token never copies
anything — the new K/V lands in the next free slot of the sequence's
last block, and a fresh block is taken from the free list only when
the last one fills. Fragmentation is bounded to < one block per
sequence instead of the (max_seq_len - actual_len) waste of a
contiguous per-request cache — the source of the >= 45% memory win the
serving bench gates.

Host/device split:

* :class:`BlockAllocator` / :class:`BlockTable` are pure-host
  bookkeeping (free list, per-sequence id lists, high-water mark) —
  cheap python between decode steps, never traced.
* :class:`PagedKVCache` owns the device pools — one
  ``[layers, num_blocks, block_size, heads, head_dim]`` array for K
  and one for V — and the jnp scatter/gather helpers the compiled
  decode program uses. The pools are donated through the decode
  program, so appends are in-place on device.

Block 0 is RESERVED as the garbage block: padded (inactive) rows of a
bucketed decode batch point their table entries at it, so their
writes land somewhere harmless and never clobber a live sequence.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["BlockAllocator", "BlockTable", "PagedKVCache",
           "blocks_for_tokens", "GARBAGE_BLOCK", "BlockFreeError"]

# physical block id every padded/inactive batch row writes into
GARBAGE_BLOCK = 0

# jitted prefill-scatter programs, keyed by array signature
_PREFILL_SCATTER_CACHE: Dict = {}


def blocks_for_tokens(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``n_tokens`` (ceil division)."""
    return -(-int(n_tokens) // int(block_size))


class OutOfBlocksError(RuntimeError):
    """Free list exhausted — the scheduler turns this into an eviction."""


class BlockFreeError(ValueError):
    """A ``free()`` that would corrupt the free list: double-free,
    free of the reserved garbage block 0, an out-of-range id, or a
    duplicate WITHIN the freed list itself. The allocator validates
    the whole list before mutating anything, so a raised free leaves
    the free list exactly as it was. (``ValueError`` base keeps
    pre-typed ``except ValueError`` callers working.)"""


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` fixed-size blocks.

    Block 0 (:data:`GARBAGE_BLOCK`) is reserved at construction and is
    never handed out. ``high_water`` tracks the peak number of
    simultaneously-allocated blocks — the serving bench compares
    ``high_water * block_bytes`` against the contiguous
    max-seq-len cache a non-paged engine would have to reserve."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is reserved)")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: recently-freed blocks are re-used first (their
        # pool slots are warm in cache on real hardware)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self.high_water = 0

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int = 1) -> List[int]:
        if n > len(self._free):
            raise OutOfBlocksError(
                f"need {n} blocks, {len(self._free)} free "
                f"(of {self.num_blocks - 1} usable)")
        out = [self._free.pop() for _ in range(n)]
        self.high_water = max(self.high_water, self.used_count)
        return out

    def free(self, blocks: List[int]) -> None:
        """Return blocks to the free list. Every id is validated
        BEFORE any mutation: out-of-range, the reserved garbage block
        (:data:`GARBAGE_BLOCK`), already-free ids, and duplicates
        inside ``blocks`` itself all raise :class:`BlockFreeError`
        instead of silently corrupting the LIFO free list (a corrupt
        list hands the same block to two sequences — cross-request KV
        bleed, the worst silent failure a serving engine can have)."""
        free_now = set(self._free)
        seen = set()
        for b in blocks:
            if b == GARBAGE_BLOCK:
                raise BlockFreeError(
                    f"free of reserved garbage block {GARBAGE_BLOCK}")
            if not (0 < b < self.num_blocks):
                raise BlockFreeError(f"bad block id {b} (usable range "
                                     f"1..{self.num_blocks - 1})")
            if b in free_now:
                raise BlockFreeError(f"double free of block {b}")
            if b in seen:
                raise BlockFreeError(
                    f"block {b} appears twice in one free() call")
            seen.add(b)
        self._free.extend(blocks)

    def rebuild_free_list(self, live_block_lists) -> None:
        """Recovery path: recompute the free list as everything NOT
        owned by the given live tables — used after a block-table
        corruption, when one table's ids can no longer be trusted
        enough to ``free()`` them (a corrupt id could double-free a
        live block). Ground truth is the surviving tables; the
        corrupted sequence's blocks implicitly return to the pool."""
        used = set()
        for blocks in live_block_lists:
            used.update(int(b) for b in blocks)
        used.discard(GARBAGE_BLOCK)
        bad = [b for b in used if not (0 < b < self.num_blocks)]
        if bad:
            raise BlockFreeError(
                f"rebuild_free_list given out-of-range ids {bad} — "
                f"survivors must be validated tables")
        self._free = [b for b in range(self.num_blocks - 1, 0, -1)
                      if b not in used]
        self.high_water = max(self.high_water, len(used))


class BlockTable:
    """One sequence's ordered block ids + token count.

    ``num_tokens`` counts K/V entries actually written; appends extend
    the table lazily through the owning allocator."""

    def __init__(self, allocator: BlockAllocator):
        self._alloc = allocator
        self.blocks: List[int] = []
        self.num_tokens = 0

    @property
    def capacity(self) -> int:
        return len(self.blocks) * self._alloc.block_size

    def ensure_capacity(self, n_tokens: int) -> None:
        """Grow the table to hold ``n_tokens`` total. Raises
        :class:`OutOfBlocksError` (eviction trigger) when the free
        list cannot cover the growth — the table is left unchanged."""
        need = blocks_for_tokens(n_tokens, self._alloc.block_size) \
            - len(self.blocks)
        if need > 0:
            self.blocks.extend(self._alloc.allocate(need))

    def append_slot(self) -> tuple:
        """(physical_block, offset) for the NEXT token, growing the
        table if the current block is full. Bumps ``num_tokens``."""
        self.ensure_capacity(self.num_tokens + 1)
        bs = self._alloc.block_size
        slot = (self.blocks[self.num_tokens // bs],
                self.num_tokens % bs)
        self.num_tokens += 1
        return slot

    def release(self) -> None:
        """Free every block back to the allocator (eviction / finish)."""
        if self.blocks:
            self._alloc.free(self.blocks)
        self.blocks = []
        self.num_tokens = 0

    def padded(self, n_pages: int) -> np.ndarray:
        """int32 table row padded to ``n_pages`` with the garbage
        block (safe for bucketed kernels: dead pages are masked by the
        context length, and padded-row writes land in block 0)."""
        row = np.full((n_pages,), GARBAGE_BLOCK, np.int32)
        row[:len(self.blocks)] = self.blocks
        return row


class PagedKVCache:
    """Device pools for a whole model: K and V, each
    ``[num_layers, num_blocks, block_size, num_heads, head_dim]``.

    Pools start zeroed; stale data in freed blocks is harmless — the
    paged-attention kernel masks every slot past a sequence's context
    length, and masked probabilities are exactly 0.0 in fp32."""

    def __init__(self, num_layers: int, num_blocks: int, block_size: int,
                 num_heads: int, head_dim: int, dtype="float32"):
        import jax.numpy as jnp
        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = jnp.dtype(dtype)
        shape = (num_layers, num_blocks, block_size, num_heads, head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)

    @property
    def block_bytes(self) -> int:
        """Bytes one block holds across K+V and all layers."""
        return (2 * self.num_layers * self.block_size * self.num_heads
                * self.head_dim * self.dtype.itemsize)

    def bytes_for_blocks(self, n_blocks: int) -> int:
        return n_blocks * self.block_bytes

    def contiguous_bytes(self, batch: int, max_seq_len: int) -> int:
        """What a contiguous per-request max-seq-len cache would
        reserve for ``batch`` sequences — the paged-vs-contiguous
        comparator the serving bench gates on."""
        return (2 * self.num_layers * batch * max_seq_len
                * self.num_heads * self.head_dim * self.dtype.itemsize)

    # -- device ops (traced inside the compiled programs) ---------------
    @staticmethod
    def scatter_decode(pool, layer, phys, slot, new_kv):
        """Write one new token per sequence into ONE layer's lane:
        ``pool[layer, phys[b], slot[b]] = new_kv[b]``.
        pool: [L, N, bs, H, D]; phys/slot: int32 [B]; new_kv:
        [B, H, D]. Traced inside the compiled decode program (which
        donates the pool), per layer — the decode loop appends each
        layer's K/V right where it is produced."""
        return pool.at[:, phys, slot].set(new_kv) if layer is None \
            else pool.at[layer, phys, slot].set(new_kv)

    @staticmethod
    def scatter_prefill(pool, layer_kv, block_row, n_tokens, block_size):
        """Write a prefilled sequence's K/V into its blocks as ONE
        jitted scatter with the pool DONATED — the eager per-page
        ``.at[].set`` loop this replaces copied the ENTIRE pool once
        per page per lane (O(pool x pages) allocator traffic at
        production pool sizes). pool: [L, N, bs, H, D]; layer_kv:
        [L, T, H, D] (T >= n_tokens when the prefill ran padded);
        block_row: int array [n_pages] physical ids. The tiny scatter
        program is cached per (pool, T, n_tokens) signature."""
        import jax
        import jax.numpy as jnp
        idx = np.arange(int(n_tokens))
        phys = jnp.asarray(np.asarray(block_row)[idx // block_size],
                           jnp.int32)
        slot = jnp.asarray(idx % block_size, jnp.int32)
        key = (tuple(pool.shape), str(pool.dtype),
               tuple(layer_kv.shape), int(n_tokens))
        fn = _PREFILL_SCATTER_CACHE.get(key)
        if fn is None:
            n = int(n_tokens)
            fn = jax.jit(
                lambda p, kv, ph, sl: p.at[:, ph, sl].set(kv[:, :n]),
                donate_argnums=(0,))
            if len(_PREFILL_SCATTER_CACHE) > 1024:
                _PREFILL_SCATTER_CACHE.clear()
            _PREFILL_SCATTER_CACHE[key] = fn
        return fn(pool, layer_kv, phys, slot)

    @staticmethod
    def gather_dense(pool_layer, block_row, n_pages):
        """Dense [n_pages*bs, H, D] view of one sequence's K or V via
        its block table — the reference path's gather."""
        import jax.numpy as jnp
        idx = jnp.asarray(block_row[:n_pages], jnp.int32)
        g = pool_layer[idx]                      # [P, bs, H, D]
        return g.reshape((-1,) + g.shape[2:])
