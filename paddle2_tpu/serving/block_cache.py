"""Paged KV cache: fixed-size blocks + per-sequence block tables.

The vLLM PagedAttention memory model (Kwon et al. SOSP'23) adapted to
the TPU serving engine: the KV cache for ALL sequences lives in one
pool of fixed-size blocks per layer, and each sequence owns an ordered
list of block ids (its *block table*). Appending a token never copies
anything — the new K/V lands in the next free slot of the sequence's
last block, and a fresh block is taken from the free list only when
the last one fills. Fragmentation is bounded to < one block per
sequence instead of the (max_seq_len - actual_len) waste of a
contiguous per-request cache — the source of the >= 45% memory win the
serving bench gates.

Host/device split:

* :class:`BlockAllocator` / :class:`BlockTable` are pure-host
  bookkeeping (free list, per-sequence id lists, high-water mark) —
  cheap python between decode steps, never traced.
* :class:`PagedKVCache` owns the device pools — one
  ``[layers, num_blocks, block_size, heads, head_dim]`` array for K
  and one for V — and the jnp scatter/gather helpers the compiled
  decode program uses. The pools are donated through the decode
  program, so appends are in-place on device.

Block 0 is RESERVED as the garbage block: padded (inactive) rows of a
bucketed decode batch point their table entries at it, so their
writes land somewhere harmless and never clobber a live sequence.

**Copy-on-write sharing (ISSUE 14 / ROADMAP 2(a)).** Every allocated
block carries a REFCOUNT. ``allocate`` hands out blocks at refcount 1;
:meth:`BlockAllocator.share` adds an owner; ``free`` drops one
reference and only returns the block to the free list at refcount 0 —
so releasing a sequence that shares a system-prompt prefix can never
yank blocks out from under its siblings (eviction of a shared block is
DEFERRED by construction). Shared blocks are always FULL blocks
(appends only ever touch a private tail), which is what makes sharing
read-only and therefore exact:

* :class:`PrefixCache` — content-addressed cache of full prompt-prefix
  blocks, keyed by the block-aligned token prefix itself (a chain of
  prefix tuples, so identical content under different prefixes never
  conflates). A lookup shares the longest cached prefix into a new
  sequence's table; the cache holds its OWN reference on every cached
  block, so finished sequences leave their prefix KV resident. LRU
  eviction reclaims cache-only (refcount-1) blocks when the allocator
  runs dry — via the allocator's reclaimer hook, so schedulers see the
  reclaimable headroom without knowing the cache exists.
* :meth:`BlockTable.fork` — CoW duplication of a live sequence: full
  blocks are shared (refcount bump), ONLY the partial tail block is
  copied (:meth:`PagedKVCache.copy_block` moves the device bytes), so
  a fork costs at most one block regardless of context length.
* :meth:`BlockAllocator.rebuild_free_list` recomputes refcounts as
  claim MULTIPLICITY across the surviving tables (+ the cache's
  holds): a block claimed by two survivors is legitimately shared
  state, not corruption — the PR 11 recovery path understands sharing.
"""

from __future__ import annotations

import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["BlockAllocator", "BlockTable", "PagedKVCache",
           "PrefixCache", "HostKVTier", "audit_kv_ledger",
           "blocks_for_tokens", "GARBAGE_BLOCK", "BlockFreeError"]

# physical block id every padded/inactive batch row writes into
GARBAGE_BLOCK = 0

# jitted prefill-scatter programs, keyed by array signature
_PREFILL_SCATTER_CACHE: Dict = {}


def blocks_for_tokens(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``n_tokens`` (ceil division)."""
    return -(-int(n_tokens) // int(block_size))


class OutOfBlocksError(RuntimeError):
    """Free list exhausted — the scheduler turns this into an eviction."""


class BlockFreeError(ValueError):
    """A ``free()`` that would corrupt the free list: double-free,
    free of the reserved garbage block 0, an out-of-range id, or a
    duplicate WITHIN the freed list itself. The allocator validates
    the whole list before mutating anything, so a raised free leaves
    the free list exactly as it was. (``ValueError`` base keeps
    pre-typed ``except ValueError`` callers working.)"""


class BlockAllocator:
    """Free-list allocator over ``num_blocks`` fixed-size blocks.

    Block 0 (:data:`GARBAGE_BLOCK`) is reserved at construction and is
    never handed out. ``high_water`` tracks the peak number of
    simultaneously-allocated blocks — the serving bench compares
    ``high_water * block_bytes`` against the contiguous
    max-seq-len cache a non-paged engine would have to reserve."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is reserved)")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # LIFO free list: recently-freed blocks are re-used first (their
        # pool slots are warm in cache on real hardware)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self.high_water = 0
        # CoW plane: per-block reference count (absent/0 = free).
        # total_allocated counts allocate() handouts MONOTONICALLY and
        # NOT share() bumps — it is the "KV bytes actually materialized"
        # numerator the prefix-cache bench gate divides by requests.
        self._rc: Dict[int, int] = {}
        self.total_allocated = 0
        # optional reclaimer (the PrefixCache): consulted when the free
        # list alone cannot cover a request — must expose
        # reclaimable() -> int and reclaim(n) -> int
        self._reclaimer = None

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def refcount(self, block: int) -> int:
        """Current owner count of ``block`` (0 = on the free list)."""
        return self._rc.get(int(block), 0)

    def set_reclaimer(self, reclaimer) -> None:
        """Install the cache that can give blocks back on demand
        (``reclaimable()``/``reclaim(n)`` protocol; None clears)."""
        self._reclaimer = reclaimer

    def _reclaimable(self) -> int:
        return self._reclaimer.reclaimable() if self._reclaimer else 0

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free) + self._reclaimable()

    def allocate(self, n: int = 1) -> List[int]:
        if n > len(self._free) and self._reclaimer is not None:
            # cached prefix blocks nobody references are headroom, not
            # occupancy: LRU-evict just enough of them
            self._reclaimer.reclaim(n - len(self._free))
        if n > len(self._free):
            raise OutOfBlocksError(
                f"need {n} blocks, {len(self._free)} free "
                f"(of {self.num_blocks - 1} usable)")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._rc[b] = 1
        self.total_allocated += n
        self.high_water = max(self.high_water, self.used_count)
        return out

    def share(self, blocks: List[int]) -> List[int]:
        """Add one owner to each (already-allocated) block — the CoW
        primitive behind prefix hits and :meth:`BlockTable.fork`.
        Validates every id BEFORE bumping anything (sharing a free or
        out-of-range block would be silent cross-request KV bleed)."""
        blocks = [int(b) for b in blocks]
        for b in blocks:
            if b == GARBAGE_BLOCK:
                raise BlockFreeError(
                    f"share of reserved garbage block {GARBAGE_BLOCK}")
            if not (0 < b < self.num_blocks):
                raise BlockFreeError(f"bad block id {b} (usable range "
                                     f"1..{self.num_blocks - 1})")
            if self._rc.get(b, 0) < 1:
                raise BlockFreeError(
                    f"share of unallocated block {b}")
        for b in blocks:
            self._rc[b] += 1
        return blocks

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per block; blocks reaching refcount 0
        return to the free list. Every id is validated BEFORE any
        mutation: out-of-range, the reserved garbage block
        (:data:`GARBAGE_BLOCK`), already-free ids, and duplicates
        inside ``blocks`` itself all raise :class:`BlockFreeError`
        instead of silently corrupting the LIFO free list (a corrupt
        list hands the same block to two sequences — cross-request KV
        bleed, the worst silent failure a serving engine can have).
        A shared block survives the free with one owner fewer — the
        deferred-eviction contract."""
        seen = set()
        for b in blocks:
            if b == GARBAGE_BLOCK:
                raise BlockFreeError(
                    f"free of reserved garbage block {GARBAGE_BLOCK}")
            if not (0 < b < self.num_blocks):
                raise BlockFreeError(f"bad block id {b} (usable range "
                                     f"1..{self.num_blocks - 1})")
            if self._rc.get(b, 0) < 1:
                raise BlockFreeError(f"double free of block {b}")
            if b in seen:
                raise BlockFreeError(
                    f"block {b} appears twice in one free() call")
            seen.add(b)
        for b in blocks:
            self._rc[b] -= 1
            if self._rc[b] == 0:
                del self._rc[b]
                self._free.append(b)

    def rebuild_free_list(self, live_block_lists) -> None:
        """Recovery path: recompute the free list — and the refcounts
        — from the surviving claims. Used after a block-table
        corruption, when one table's ids can no longer be trusted
        enough to ``free()`` them (a corrupt id could double-free a
        live block). Ground truth is the surviving tables (plus the
        prefix cache's holds, which the engine passes as one more
        claim list); a block claimed by SEVERAL survivors is
        legitimately shared and its refcount is rebuilt as the claim
        multiplicity. The corrupted sequence's blocks implicitly
        return to the pool."""
        claims: Dict[int, int] = {}
        for blocks in live_block_lists:
            for b in blocks:
                b = int(b)
                if b == GARBAGE_BLOCK:
                    continue
                claims[b] = claims.get(b, 0) + 1
        bad = [b for b in claims if not (0 < b < self.num_blocks)]
        if bad:
            raise BlockFreeError(
                f"rebuild_free_list given out-of-range ids {bad} — "
                f"survivors must be validated tables")
        self._rc = dict(claims)
        self._free = [b for b in range(self.num_blocks - 1, 0, -1)
                      if b not in claims]
        self.high_water = max(self.high_water, len(claims))


class BlockTable:
    """One sequence's ordered block ids + token count.

    ``num_tokens`` counts K/V entries actually written; appends extend
    the table lazily through the owning allocator."""

    def __init__(self, allocator: BlockAllocator):
        self._alloc = allocator
        self.blocks: List[int] = []
        self.num_tokens = 0

    @property
    def capacity(self) -> int:
        return len(self.blocks) * self._alloc.block_size

    def ensure_capacity(self, n_tokens: int) -> None:
        """Grow the table to hold ``n_tokens`` total. Raises
        :class:`OutOfBlocksError` (eviction trigger) when the free
        list cannot cover the growth — the table is left unchanged."""
        need = blocks_for_tokens(n_tokens, self._alloc.block_size) \
            - len(self.blocks)
        if need > 0:
            self.blocks.extend(self._alloc.allocate(need))

    def append_slot(self) -> tuple:
        """(physical_block, offset) for the NEXT token, growing the
        table if the current block is full. Bumps ``num_tokens``.
        Appending INTO a shared block (refcount > 1) is refused: the
        CoW invariant is that shared blocks are always FULL (prefix
        hits and forks only ever share whole blocks), so a shared
        append target means the bookkeeping upstream is broken and a
        write would bleed into a sibling sequence's KV."""
        self.ensure_capacity(self.num_tokens + 1)
        bs = self._alloc.block_size
        target = self.blocks[self.num_tokens // bs]
        if self.num_tokens % bs and self._alloc.refcount(target) > 1:
            raise BlockFreeError(
                f"append into shared block {target} (refcount "
                f"{self._alloc.refcount(target)}) — shared blocks are "
                f"read-only; fork() copies the partial tail")
        slot = (target, self.num_tokens % bs)
        self.num_tokens += 1
        return slot

    def attach_shared(self, blocks: List[int]) -> None:
        """Adopt already-shared blocks (the caller — a prefix-cache
        hit — bumped their refcounts) as this table's leading blocks.
        Only valid on an EMPTY table: shared blocks are a prefix, by
        construction."""
        if self.blocks:
            raise BlockFreeError(
                "attach_shared on a non-empty table — shared prefix "
                "blocks must come first")
        self.blocks = [int(b) for b in blocks]

    def fork(self) -> Tuple["BlockTable", Optional[Tuple[int, int]]]:
        """Copy-on-write duplicate of this table: full blocks are
        SHARED (refcount bump — zero bytes moved), only the partial
        tail block is freshly allocated. Returns ``(new_table,
        copy)`` where ``copy`` is ``(src_block, dst_block)`` for the
        device-side tail copy the caller must perform
        (:meth:`PagedKVCache.copy_block` on both pools), or ``None``
        when the token count is block-aligned."""
        bs = self._alloc.block_size
        n_full = self.num_tokens // bs
        new = BlockTable(self._alloc)
        shared = self.blocks[:n_full]
        if shared:
            self._alloc.share(shared)
        new.blocks = list(shared)
        copy = None
        if self.num_tokens % bs:
            src = self.blocks[n_full]
            dst = self._alloc.allocate(1)[0]
            new.blocks.append(dst)
            copy = (src, dst)
        new.num_tokens = self.num_tokens
        return new, copy

    def truncate(self) -> List[int]:
        """Roll back surplus tail blocks past what ``num_tokens``
        needs — the speculative-decoding rejection path (a verify
        round reserves ``k + 1`` slots up front; the rejected tail's
        blocks go straight back). Returns the freed block ids."""
        keep = blocks_for_tokens(self.num_tokens, self._alloc.block_size)
        surplus = self.blocks[keep:]
        if surplus:
            self._alloc.free(surplus)
            self.blocks = self.blocks[:keep]
        return surplus

    def release(self) -> None:
        """Drop this table's reference on every block (eviction /
        finish); unshared blocks return to the allocator, shared ones
        stay with their surviving owners."""
        if self.blocks:
            self._alloc.free(self.blocks)
        self.blocks = []
        self.num_tokens = 0

    def padded(self, n_pages: int) -> np.ndarray:
        """int32 table row padded to ``n_pages`` with the garbage
        block (safe for bucketed kernels: dead pages are masked by the
        context length, and padded-row writes land in block 0)."""
        row = np.full((n_pages,), GARBAGE_BLOCK, np.int32)
        row[:len(self.blocks)] = self.blocks
        return row


class PagedKVCache:
    """Device pools for a whole model: K and V, each
    ``[num_layers, num_blocks, block_size, num_heads, head_dim]``.

    Pools start zeroed; stale data in freed blocks is harmless — the
    paged-attention kernel masks every slot past a sequence's context
    length, and masked probabilities are exactly 0.0 in fp32."""

    def __init__(self, num_layers: int, num_blocks: int, block_size: int,
                 num_heads: int, head_dim: int, dtype="float32"):
        import jax.numpy as jnp
        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = jnp.dtype(dtype)
        shape = (num_layers, num_blocks, block_size, num_heads, head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)

    @property
    def block_bytes(self) -> int:
        """Bytes one block holds across K+V and all layers."""
        return (2 * self.num_layers * self.block_size * self.num_heads
                * self.head_dim * self.dtype.itemsize)

    def bytes_for_blocks(self, n_blocks: int) -> int:
        return n_blocks * self.block_bytes

    def contiguous_bytes(self, batch: int, max_seq_len: int) -> int:
        """What a contiguous per-request max-seq-len cache would
        reserve for ``batch`` sequences — the paged-vs-contiguous
        comparator the serving bench gates on."""
        return (2 * self.num_layers * batch * max_seq_len
                * self.num_heads * self.head_dim * self.dtype.itemsize)

    # -- device ops (traced inside the compiled programs) ---------------
    @staticmethod
    def scatter_decode(pool, layer, phys, slot, new_kv):
        """Write one new token per sequence into ONE layer's lane:
        ``pool[layer, phys[b], slot[b]] = new_kv[b]``.
        pool: [L, N, bs, H, D]; phys/slot: int32 [B]; new_kv:
        [B, H, D]. Traced inside the compiled decode program (which
        donates the pool), per layer — the decode loop appends each
        layer's K/V right where it is produced."""
        return pool.at[:, phys, slot].set(new_kv) if layer is None \
            else pool.at[layer, phys, slot].set(new_kv)

    @staticmethod
    def scatter_prefill(pool, layer_kv, block_row, n_tokens, block_size,
                        start: int = 0):
        """Write a prefilled sequence's K/V into its blocks as ONE
        jitted scatter with the pool DONATED — the eager per-page
        ``.at[].set`` loop this replaces copied the ENTIRE pool once
        per page per lane (O(pool x pages) allocator traffic at
        production pool sizes). pool: [L, N, bs, H, D]; layer_kv:
        [L, T, H, D] (T >= n_tokens when the prefill ran padded);
        block_row: int array [n_pages] physical ids. ``start`` skips
        the leading positions — a prefix-cache hit must NOT rewrite
        the shared blocks it reads (their bytes belong to every
        sharer), so only the private tail ``[start, n_tokens)`` is
        scattered. The tiny scatter program is cached per
        (pool, T, start, n_tokens) signature."""
        import jax
        import jax.numpy as jnp
        start = int(start)
        if start >= int(n_tokens):
            return pool
        idx = np.arange(start, int(n_tokens))
        phys = jnp.asarray(np.asarray(block_row)[idx // block_size],
                           jnp.int32)
        slot = jnp.asarray(idx % block_size, jnp.int32)
        key = (tuple(pool.shape), str(pool.dtype),
               tuple(layer_kv.shape), start, int(n_tokens))
        fn = _PREFILL_SCATTER_CACHE.get(key)
        if fn is None:
            n = int(n_tokens)
            fn = jax.jit(
                lambda p, kv, ph, sl: p.at[:, ph, sl].set(
                    kv[:, start:n]),
                donate_argnums=(0,))
            if len(_PREFILL_SCATTER_CACHE) > 1024:
                _PREFILL_SCATTER_CACHE.clear()
            _PREFILL_SCATTER_CACHE[key] = fn
        return fn(pool, layer_kv, phys, slot)

    @staticmethod
    def copy_block(pool, src: int, dst: int):
        """Device-side CoW tail copy for :meth:`BlockTable.fork`:
        ``pool[:, dst] = pool[:, src]`` across all layers, as one
        jitted donated program (cached per pool signature)."""
        import jax
        import jax.numpy as jnp
        key = ("copy", tuple(pool.shape), str(pool.dtype))
        fn = _PREFILL_SCATTER_CACHE.get(key)
        if fn is None:
            fn = jax.jit(
                lambda p, s, d: p.at[:, d].set(p[:, s]),
                donate_argnums=(0,))
            _PREFILL_SCATTER_CACHE[key] = fn
        return fn(pool, jnp.asarray(int(src), jnp.int32),
                  jnp.asarray(int(dst), jnp.int32))

    @staticmethod
    def gather_dense(pool_layer, block_row, n_pages):
        """Dense [n_pages*bs, H, D] view of one sequence's K or V via
        its block table — the reference path's gather."""
        import jax.numpy as jnp
        idx = jnp.asarray(block_row[:n_pages], jnp.int32)
        g = pool_layer[idx]                      # [P, bs, H, D]
        return g.reshape((-1,) + g.shape[2:])


class HostKVTier:
    """Pinned-host-DRAM spill tier for cold prefix blocks (ISSUE 16).

    The second rung of the HBM -> host -> peer-DCN KV ladder: when the
    allocator's reclaimer would DISCARD a cold cached prefix block,
    the block's raw K/V bytes are copied here first — keyed by the
    SAME chained prefix-tuple key the :class:`PrefixCache` uses, so a
    later hit on the spilled prefix fetches the bytes back instead of
    re-prefilling. Host entries are BYTES, not allocator block ids:
    the allocator's ownership invariant (free + referenced == usable,
    every block owned exactly once) is untouched by spilling, which is
    what keeps ``rebuild_free_list`` auditable across tiers.

    Every payload is stamped with a CRC at spill time and verified at
    fetch: a scribbled spill (chaos ``corrupt_spill_block``, a real
    host-DMA fault) is DROPPED at fetch, so the consumer falls back to
    re-prefill — corruption can cost time, never correctness. The tier
    keeps its own LRU ledger; ``capacity_blocks`` bounds occupancy
    (oldest spills evicted — the ladder's final discard)."""

    def __init__(self, capacity_blocks: Optional[int] = None):
        # key -> (k_bytes, v_bytes, crc); _lru tracks recency
        self._entries: Dict[tuple, Tuple[np.ndarray, np.ndarray, int]] = {}
        self._lru: "OrderedDict[tuple, None]" = OrderedDict()
        self.capacity_blocks = capacity_blocks
        self.spilled = 0          # put()s (blocks entering the tier)
        self.fetched = 0          # pop()s (blocks promoted back to HBM)
        self.evictions = 0        # LRU discards past capacity
        self.corrupt_drops = 0    # CRC mismatches dropped at get()

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return key in self._entries

    @staticmethod
    def _crc(k_np: np.ndarray, v_np: np.ndarray) -> int:
        return zlib.crc32(v_np.tobytes(), zlib.crc32(k_np.tobytes()))

    def put(self, key: tuple, k_np: np.ndarray, v_np: np.ndarray) -> None:
        """Spill one block's K/V bytes under ``key`` (host-owned
        copies; the CRC is stamped from the copies so a later fetch
        verifies exactly what was stored)."""
        k = np.array(k_np, copy=True)
        v = np.array(v_np, copy=True)
        self._entries[key] = (k, v, self._crc(k, v))
        self._lru[key] = None
        self._lru.move_to_end(key)
        self.spilled += 1
        while self.capacity_blocks is not None and \
                len(self._entries) > self.capacity_blocks:
            old, _ = self._lru.popitem(last=False)
            del self._entries[old]
            self.evictions += 1

    def get(self, key: tuple
            ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Verified, NON-destructive read. A CRC mismatch drops the
        entry and returns None — the caller re-prefills; serving a
        scribbled payload would be silent KV corruption."""
        ent = self._entries.get(key)
        if ent is None:
            return None
        k, v, crc = ent
        if self._crc(k, v) != crc:
            del self._entries[key]
            del self._lru[key]
            self.corrupt_drops += 1
            return None
        self._lru.move_to_end(key)
        return k, v

    def pop(self, key: tuple) -> None:
        """Retire ``key`` after a successful promotion back to HBM —
        a prefix lives in exactly one tier at a time."""
        if key in self._entries:
            del self._entries[key]
            del self._lru[key]
            self.fetched += 1

    def keys(self) -> List[tuple]:
        return list(self._entries)

    def corrupt_one(self) -> Optional[tuple]:
        """Chaos helper (``corrupt_spill_block``): flip one byte of
        the OLDEST entry's K payload, keeping the stored CRC — the
        next ``get`` must detect it. Returns the key hit (None when
        the tier is empty). Deterministic: oldest entry, first byte."""
        for key in self._lru:
            k, v, crc = self._entries[key]
            k = np.array(k, copy=True)
            raw = k.view(np.uint8).reshape(-1)
            raw[0] ^= 0xFF
            self._entries[key] = (k, v, crc)
            return key
        return None


def audit_kv_ledger(allocator: BlockAllocator, live_block_lists,
                    prefix_cache: Optional["PrefixCache"] = None,
                    in_migration=(), host_tier: Optional[HostKVTier] = None
                    ) -> Dict[str, int]:
    """Cross-tier ownership audit (ISSUE 16): every usable block is
    owned EXACTLY once — on the free list, or referenced with a
    refcount equal to its claim multiplicity across the live tables,
    the prefix cache's own holds, and any in-migration claim list —
    and ``free + claimed == usable``. Host-tier entries are byte
    payloads, never allocator ids, so they cannot alias device blocks
    by construction; the audit reports their count so the property
    test can close the whole ladder. Raises :class:`BlockFreeError`
    on any violation; returns the tier census when clean."""
    claims: Dict[int, int] = {}
    lists = [list(l) for l in live_block_lists]
    if prefix_cache is not None:
        lists.append(prefix_cache.held_blocks())
    lists.append(list(in_migration))
    for lst in lists:
        for b in lst:
            b = int(b)
            if b == GARBAGE_BLOCK:
                continue
            claims[b] = claims.get(b, 0) + 1
    free = list(allocator._free)
    usable = allocator.num_blocks - 1
    if len(set(free)) != len(free):
        raise BlockFreeError("free list holds a duplicate id")
    for b in free:
        if not (0 < b < allocator.num_blocks):
            raise BlockFreeError(f"free list holds bad id {b}")
        if b in claims:
            raise BlockFreeError(
                f"block {b} is both free and claimed — owned twice")
    for b, c in claims.items():
        if not (0 < b < allocator.num_blocks):
            raise BlockFreeError(f"claim on out-of-range block {b}")
        if allocator.refcount(b) != c:
            raise BlockFreeError(
                f"block {b}: refcount {allocator.refcount(b)} != claim "
                f"multiplicity {c}")
    for b in allocator._rc:
        if b not in claims:
            raise BlockFreeError(
                f"block {b} allocated (rc={allocator._rc[b]}) but "
                f"claimed by no table, cache, or migration")
    if len(free) + len(claims) != usable:
        raise BlockFreeError(
            f"ledger does not close: {len(free)} free + {len(claims)} "
            f"claimed != {usable} usable")
    return {"free": len(free), "claimed": len(claims),
            "host_tier": len(host_tier) if host_tier is not None else 0,
            "in_migration": len(list(in_migration))}


class PrefixCache:
    """Content-addressed cache of full prompt-prefix blocks (CoW
    prefix sharing, the vLLM automatic-prefix-caching design).

    Keying: block ``i`` of a prompt is cached under the TUPLE of the
    first ``(i+1) * block_size`` tokens — a chain of prefix keys, so a
    block's identity includes everything before it (the same 16 tokens
    after two different prefixes hold DIFFERENT KV — position and
    history are baked into the values). KV at a position depends only
    on the tokens at and before it, so any request whose prompt starts
    with a cached prefix can share those blocks bit-exactly.

    Reference discipline: the cache holds its OWN reference on every
    cached block (``share`` at insert), so cached KV survives its
    inserting sequence. A block whose only reference is the cache's
    (refcount 1) is *reclaimable*; the allocator's reclaimer hook
    LRU-evicts exactly as many as a starved ``allocate`` needs. Blocks
    still shared with live sequences (refcount > 1) are NEVER
    reclaimed — eviction of a shared block is deferred until its last
    sequence releases it.
    """

    def __init__(self, allocator: BlockAllocator,
                 max_blocks: Optional[int] = None,
                 host_tier: Optional[HostKVTier] = None):
        self._alloc = allocator
        self.block_size = allocator.block_size
        # prefix-key tuple -> block id; _lru tracks use recency for
        # reclaim order (oldest first)
        self._entries: Dict[tuple, int] = {}
        self._lru: "OrderedDict[tuple, int]" = OrderedDict()
        self.max_blocks = max_blocks
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # ISSUE 16 tiering: host-DRAM spill tier + the device-byte I/O
        # hooks (engine-installed: gather(block) -> (k, v) host arrays,
        # scatter(block, k, v) writes them back) and an optional peer
        # source (fleet-installed: missing keys -> payloads + modeled
        # DCN seconds). All None = PR 13 HBM-only behavior.
        self.host_tier = host_tier
        self._gather = None
        self._scatter = None
        self._peer_fetch = None
        self.host_fetches = 0
        self.peer_fetches = 0
        self.spills = 0
        # per-lookup attribution for the admission path's stall
        # accounting (the engine charges spill_fetch_s from these)
        self.last_host_fetched = 0
        self.last_peer_fetched = 0
        self.last_peer_fetch_s = 0.0
        allocator.set_reclaimer(self)

    def set_spill_io(self, gather, scatter) -> None:
        """Install the device-byte movers the spill tier rides on:
        ``gather(block) -> (k_np, v_np)`` and
        ``scatter(block, k_np, v_np)`` (the engine owns the pools —
        they are reassigned after every donated program, so the cache
        must go through closures, not a pool reference)."""
        self._gather = gather
        self._scatter = scatter

    def set_peer_source(self, fetch) -> None:
        """Install the fleet's peer tier: ``fetch(missing_keys) ->
        (payloads, modeled_seconds)`` returns device bytes for a
        leading run of ``missing_keys`` from ONE peer over DCN — or
        ``([], 0.0)`` when no peer holds them or the modeled transfer
        loses to modeled re-prefill (the registry owns that cost-model
        decision)."""
        self._peer_fetch = fetch

    def __len__(self) -> int:
        return len(self._entries)

    def _keys(self, tokens) -> List[tuple]:
        bs = self.block_size
        return [tuple(tokens[:(i + 1) * bs])
                for i in range(len(tokens) // bs)]

    # -- lookup / insert -------------------------------------------------
    def lookup(self, tokens, share: bool = True
               ) -> Tuple[List[int], int]:
        """Longest cached block-aligned prefix of ``tokens`` ->
        ``(blocks, n_cached_tokens)``. With ``share=True`` (the commit
        path) every returned block gains this sequence's reference and
        the hit/miss ledger advances; ``share=False`` peeks (admission
        feasibility checks)."""
        keys = self._keys(tokens)
        blocks: List[int] = []
        for key in keys:
            b = self._entries.get(key)
            if b is None:
                break
            blocks.append(b)
            if share:
                self._lru.move_to_end(key)
        self.last_host_fetched = 0
        self.last_peer_fetched = 0
        self.last_peer_fetch_s = 0.0
        if share:
            if blocks:
                # share the HBM chain FIRST: the fetch loops below
                # allocate, which may trigger reclaim — the
                # requester's references pin these blocks (refcount 2)
                # so the reclaimer cannot evict them mid-lookup
                self._alloc.share(blocks)
            self._fetch_host(keys, blocks)
            self._fetch_peer(keys, blocks)
            if blocks:
                self.hits += 1
            else:
                self.misses += 1
        return blocks, len(blocks) * self.block_size

    def _adopt_fetched(self, key: tuple, payload) -> Optional[int]:
        """Promote one fetched payload into a fresh HBM block owned by
        the cache (allocate's reference) AND shared to the requester.
        Returns the block id, or None when the pool cannot cover it
        (the caller stops fetching — re-prefill covers the rest)."""
        if self._scatter is None:
            return None
        try:
            nb = self._alloc.allocate(1)[0]
        except OutOfBlocksError:
            return None
        self._scatter(nb, payload[0], payload[1])
        self._entries[key] = nb
        self._lru[key] = nb
        self._alloc.share([nb])
        return nb

    def _fetch_host(self, keys: List[tuple], blocks: List[int]) -> int:
        """Extend a commit-path lookup's chain from the host tier:
        verified payloads are scattered back into fresh HBM blocks
        (spill-tier promotion). Stops at the first miss, CRC drop, or
        allocation failure — everything past that re-prefills."""
        if self.host_tier is None:
            return 0
        fetched = 0
        for key in keys[len(blocks):]:
            payload = self.host_tier.get(key)
            if payload is None:
                break
            nb = self._adopt_fetched(key, payload)
            if nb is None:
                break
            self.host_tier.pop(key)
            blocks.append(nb)
            fetched += 1
        self.host_fetches += fetched
        self.last_host_fetched = fetched
        return fetched

    def _fetch_peer(self, keys: List[tuple], blocks: List[int]) -> int:
        """Extend the chain from a peer engine over DCN (the fleet
        registry's cost-model decision already chose transfer over
        re-prefill when this returns payloads)."""
        if self._peer_fetch is None:
            return 0
        missing = keys[len(blocks):]
        if not missing:
            return 0
        payloads, seconds = self._peer_fetch(missing)
        if not payloads:
            return 0
        fetched = 0
        for key, payload in zip(missing, payloads):
            nb = self._adopt_fetched(key, payload)
            if nb is None:
                break
            blocks.append(nb)
            fetched += 1
        if fetched:
            self.peer_fetches += fetched
            self.last_peer_fetched = fetched
            # a partial promotion pays for the blocks it landed
            self.last_peer_fetch_s = float(seconds) * (fetched
                                                       / len(payloads))
        return fetched

    def cached_prefix_tokens(self, tokens) -> int:
        """Read-only: the longest block-aligned prefix of ``tokens``
        servable WITHOUT recompute from this engine's tiers (HBM chain
        + host-tier extension). No references taken, no fetches — the
        prefix-affinity router and the peer advertisement both consult
        this."""
        n = 0
        for key in self._keys(tokens):
            if key in self._entries or (self.host_tier is not None
                                        and key in self.host_tier):
                n += 1
            else:
                break
        return n * self.block_size

    def export_chain(self, keys: List[tuple]
                     ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Gather the payload bytes for a leading run of ``keys`` this
        engine holds (HBM first, then host tier) — the peer-fetch /
        migration SOURCE side. Stops at the first miss or corrupt
        spill. Copies leave the local tiers untouched."""
        out: List[Tuple[np.ndarray, np.ndarray]] = []
        for key in keys:
            b = self._entries.get(key)
            if b is not None and self._gather is not None:
                out.append(self._gather(b))
                continue
            payload = (self.host_tier.get(key)
                       if self.host_tier is not None else None)
            if payload is None:
                break
            out.append(payload)
        return out

    def insert(self, tokens, blocks: List[int],
               n_prefix_tokens: Optional[int] = None) -> int:
        """Register the FULL blocks covering ``tokens[:n_prefix]``
        (default: the whole list) from a just-prefilled table. Already
        -cached prefixes are skipped (the owning sequence simply keeps
        its private copy — correct either way, the cached block serves
        future lookups). Each newly cached block gains the cache's own
        reference. Returns how many blocks were newly cached."""
        n = len(tokens) if n_prefix_tokens is None \
            else min(int(n_prefix_tokens), len(tokens))
        added = 0
        for i, key in enumerate(self._keys(list(tokens)[:n])):
            if key in self._entries:
                continue
            b = int(blocks[i])
            self._alloc.share([b])
            self._entries[key] = b
            self._lru[key] = b
            added += 1
        if self.max_blocks is not None and len(self._entries) > \
                self.max_blocks:
            self.reclaim(len(self._entries) - self.max_blocks)
        return added

    # -- accounting ------------------------------------------------------
    def held_blocks(self) -> List[int]:
        """Every block the cache itself holds a reference on — ONE
        claim list for ``rebuild_free_list`` (the cache is a survivor
        too)."""
        return list(self._entries.values())

    def holds(self, block: int) -> bool:
        return int(block) in set(self._entries.values())

    def shared_bytes(self, block_bytes: int) -> int:
        """KV bytes currently deduplicated: for every cached block,
        each reference beyond the first would have been a private copy
        without the cache."""
        return sum(max(self._alloc.refcount(b) - 1, 0)
                   for b in self._entries.values()) * int(block_bytes)

    # -- reclaim (the allocator hook) ------------------------------------
    def reclaimable(self) -> int:
        """Blocks the cache could hand back RIGHT NOW: cached blocks
        whose only reference is the cache's own."""
        return sum(1 for b in self._entries.values()
                   if self._alloc.refcount(b) == 1)

    def reclaim(self, n: int) -> int:
        """Evict up to ``n`` reclaimable blocks, least-recently-used
        first; blocks still shared with live sequences are skipped
        (deferred until their last release). With a host tier wired
        (ISSUE 16) eviction prefers SPILL over discard: the block's
        bytes move to host DRAM under the same prefix key before the
        HBM block returns to the free list, so cache pressure degrades
        to a fetch, not a recompute. Returns how many were actually
        freed."""
        if n <= 0:
            return 0
        freed = 0
        for key in list(self._lru.keys()):
            if freed >= n:
                break
            b = self._entries[key]
            if self._alloc.refcount(b) != 1:
                continue
            if self.host_tier is not None and self._gather is not None:
                k_np, v_np = self._gather(b)
                self.host_tier.put(key, k_np, v_np)
                self.spills += 1
            del self._entries[key]
            del self._lru[key]
            self._alloc.free([b])
            self.evictions += 1
            freed += 1
        return freed
