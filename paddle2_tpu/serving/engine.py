"""ServingEngine: continuous batching + paged KV over a GPT model.

The production serving loop (ROADMAP item 2): requests come in via
``submit()``, the engine prefills them into paged KV blocks, and every
``decode_once()`` runs ONE bucketed compiled decode step over the
whole running batch — admissions and evictions happen between steps
(iteration-level scheduling). Construct it from a live
``GPTForCausalLM`` or from a ``jit.save``'d artifact (the artifact's
weights are loaded into a rebuilt architecture — the exported forward
program itself has no KV surface to page).

Decode-step telemetry flows through the PR 7 metrics plane when it is
enabled: ``serving_*`` counters/gauges plus one step window per decode
step with EXPLICIT token counts (``step_end(tokens=...)``) — serving
never relies on the train-step token heuristic, whose int-id shape
sniffing must not see block tables or int8 KV payloads as token
batches. The modeled step cost (XLA cost model) rides in the step
record as ``modeled_step_s`` so ``perf_doctor diff`` can compare
serving streams deterministically.

Greedy decoding; time enters only through the caller-supplied ``now``
stamps (the serving bench passes a virtual cost-model clock — no wall
clocks in any gate).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence as Seq, Tuple

import numpy as np

from .block_cache import (BlockAllocator, HostKVTier, PagedKVCache,
                          PrefixCache, blocks_for_tokens, GARBAGE_BLOCK)
from .model_runner import PagedGPTRunner
from .reliability import (EngineFailedError, PromptTooLongError,
                          ReliabilityConfig, RequestRejected,
                          flight_record as _flight_record)
from .scheduler import (ContinuousBatchingScheduler, Request, SchedulerConfig,
                        Sequence, SeqState)
from .spec import SpeculativeConfig, accept_drafts, ngram_draft

__all__ = ["EngineConfig", "ServingEngine"]


def _pow2_ladder(lo: int, hi: int) -> Tuple[int, ...]:
    out, v = [], lo
    while v < hi:
        out.append(v)
        v *= 2
    out.append(hi)
    return tuple(sorted(set(out)))


@dataclass
class EngineConfig:
    block_size: int = 16
    num_blocks: int = 64
    max_batch: int = 8
    # None -> power-of-two ladders derived from max_batch /
    # max_model_len; the compiled decode program count is bounded by
    # len(batch_buckets) * len(page_buckets)
    batch_buckets: Optional[Tuple[int, ...]] = None
    page_buckets: Optional[Tuple[int, ...]] = None
    prefill_budget_tokens: int = 512
    weight_only_int8: bool = False
    # also quantize the lm_head / logits matmul (shared-embedding
    # aware: the fp embedding table keeps serving the lookup) through
    # quantization.quantize_lm_head — the same entry point the
    # training-time quantized_lm_head config calibrates against
    weight_only_lm_head: bool = False
    max_model_len: Optional[int] = None
    kv_dtype: str = "float32"
    interpret: Optional[bool] = None
    # admission control / load shedding (None = unbounded PR 9
    # behavior); see serving.reliability.ReliabilityConfig
    reliability: Optional[ReliabilityConfig] = None
    # copy-on-write prefix caching (ISSUE 14): shared system prompts
    # collapse to one refcounted KV copy; prefix_cache_blocks bounds
    # the cache (None = bounded only by LRU reclaim pressure)
    enable_prefix_cache: bool = False
    prefix_cache_blocks: Optional[int] = None
    # speculative decoding (None = off): see serving.spec
    spec: Optional[SpeculativeConfig] = None
    # split-K width for the paged-attention kernel (None = the
    # kernel's own VMEM-fit auto dispatch — PR 9 behavior at every
    # context PR 9 could serve)
    split_pages: Optional[int] = None
    # fleet-global KV tiering (ISSUE 16, needs enable_prefix_cache):
    # cold prefix blocks SPILL to a host-DRAM tier instead of being
    # discarded, and fetch back on hit — priced over the shared
    # offload host link (cost_model.DEFAULT_HOST_GBPS, the same
    # channel autotune's offload-remat policy models). With tiering on
    # the virtual clock also charges prefill for the UNCACHED tail
    # only (a cached prefix is KV that exists — the real system skips
    # its compute), which is what lets migration beat re-prefill.
    enable_kv_spill: bool = False
    # host-tier capacity in blocks (None = unbounded)
    host_tier_blocks: Optional[int] = None
    # host-link override in GB/s (None = env / shared default)
    host_link_gbps: Optional[float] = None


class ServingEngine:
    """Continuous-batching serving engine over one GPT model."""

    def __init__(self, model=None, *, artifact_path: Optional[str] = None,
                 artifact_params_path: Optional[str] = None,
                 gpt_config=None, config: Optional[EngineConfig] = None):
        self.config = config or EngineConfig()
        if model is None:
            if artifact_path is None:
                raise ValueError("pass model= or artifact_path=")
            model = self._load_artifact(artifact_path, gpt_config,
                                        artifact_params_path)
        cfg = model.cfg
        if getattr(cfg, "stacked_blocks", False):
            raise ValueError(
                "serving requires addressable blocks; rebuild with "
                "stacked_blocks=False (the decode program wires the "
                "paged append between qkv and attention per block)")
        self.model = model
        model.eval()
        self.max_model_len = int(self.config.max_model_len
                                 or cfg.max_position_embeddings)
        if self.max_model_len > cfg.max_position_embeddings:
            # jnp gathers CLAMP out-of-range indices, so positions past
            # the wpe table would silently decode with the wrong
            # embedding instead of raising
            raise ValueError(
                f"max_model_len {self.max_model_len} exceeds the "
                f"model's max_position_embeddings "
                f"{cfg.max_position_embeddings}")
        if self.config.weight_only_int8:
            from ..quantization import weight_only_quantize
            # projection matmuls only: qkv/out_proj/up/down inside the
            # blocks — embeddings and the (tied) head stay fp unless
            # weight_only_lm_head opts the logits matmul in below
            for block in model.gpt.h:
                weight_only_quantize(block)
        if self.config.weight_only_lm_head:
            from ..quantization import quantize_lm_head
            quantize_lm_head(model)
        self.cache = PagedKVCache(
            cfg.num_layers, self.config.num_blocks, self.config.block_size,
            cfg.num_heads, cfg.head_dim, dtype=self.config.kv_dtype)
        self.allocator = BlockAllocator(self.config.num_blocks,
                                        self.config.block_size)
        max_pages = blocks_for_tokens(self.max_model_len,
                                      self.config.block_size)
        # a speculative verify round rides k extra rows per sequence
        # through the SAME decode program family — the batch-bucket
        # ladder must cover the widest verify batch so the program
        # census stays inside the bucket grid (the PR 9 gate)
        max_rows = self.config.max_batch
        if self.config.spec is not None:
            max_rows *= 1 + self.config.spec.num_draft_tokens
            if self.config.batch_buckets is not None and \
                    max(self.config.batch_buckets) < max_rows:
                # fail at construction, not mid-decode: the first full
                # verify round would otherwise hit batch_bucket() with
                # a row count the explicit ladder cannot cover
                raise ValueError(
                    f"batch_buckets {self.config.batch_buckets} cannot "
                    f"cover speculative verify rows (max_batch "
                    f"{self.config.max_batch} x (1 + "
                    f"{self.config.spec.num_draft_tokens} drafts) = "
                    f"{max_rows})")
        sched_cfg = SchedulerConfig(
            max_batch=self.config.max_batch,
            batch_buckets=(self.config.batch_buckets
                           or _pow2_ladder(1, max_rows)),
            page_buckets=(self.config.page_buckets
                          or _pow2_ladder(1, max_pages)),
            prefill_budget_tokens=self.config.prefill_budget_tokens,
            reliability=self.config.reliability)
        self.scheduler = ContinuousBatchingScheduler(sched_cfg,
                                                     self.allocator)
        self.prefix_cache: Optional[PrefixCache] = None
        self.host_tier: Optional[HostKVTier] = None
        if self.config.enable_prefix_cache:
            if self.config.enable_kv_spill:
                self.host_tier = HostKVTier(self.config.host_tier_blocks)
            self.prefix_cache = PrefixCache(
                self.allocator, max_blocks=self.config.prefix_cache_blocks,
                host_tier=self.host_tier)
            if self.host_tier is not None:
                self.prefix_cache.set_spill_io(self._kv_gather_block,
                                               self._kv_scatter_block)
            self.scheduler.prefix_cache = self.prefix_cache
        # metric-counter snapshot for the KV-tier totals (spill/fetch
        # events fire deep inside the allocator's reclaim hook, so the
        # engine emits deltas rather than instrumenting the cache)
        self._kv_counts: Dict[str, int] = {}
        self.runner = PagedGPTRunner(model, cfg.num_heads, cfg.head_dim,
                                     interpret=self.config.interpret,
                                     split_pages=self.config.split_pages)
        self.spec_accepted = 0
        self.spec_rejected = 0
        self._next_req_id = 0
        self._seqs: Dict[int, Sequence] = {}
        self.decode_steps = 0
        # failure plane: set by fail() (chaos kill_engine, an operator
        # kill, a poisoned device) — a failed engine refuses all work
        # and its in-flight sequences are harvested for failover
        self.engine_id = 0
        self.failed = False
        self.fail_reason: Optional[str] = None
        self.failed_t: Optional[float] = None

    @property
    def engine_id(self) -> int:
        return self._engine_id

    @engine_id.setter
    def engine_id(self, value: int) -> None:
        # mirrored onto the scheduler so ITS flight/trace spans carry
        # the same lane id the engine's do (the router re-numbers
        # engines after construction — a copied id would go stale)
        self._engine_id = int(value)
        self.scheduler.engine_id = self._engine_id

    # -- construction helpers --------------------------------------------
    @staticmethod
    def _load_artifact(artifact_path: str, gpt_config,
                       params_path: Optional[str] = None):
        """Rebuild the architecture from ``gpt_config`` and load the
        ``jit.save``'d weights into it. ``params_path`` overrides the
        prefix-derived weights file — the same contract
        ``Config.set_model(prog_file, params_file)`` gives the
        Predictor path."""
        if gpt_config is None:
            raise ValueError(
                "artifact_path needs gpt_config= (the architecture is "
                "rebuilt; the serialized program has no pageable KV)")
        from ..jit.api import load as jit_load
        from ..models.gpt import GPTForCausalLM
        loaded = jit_load(artifact_path, params_path=params_path)
        model = GPTForCausalLM(gpt_config)
        model.set_state_dict(loaded.state_dict())
        return model

    # -- KV tier I/O (ISSUE 16) ------------------------------------------
    def _kv_gather_block(self, block: int):
        """One block's K/V bytes, device -> host arrays (the spill /
        peer-export path). Goes through ``self.cache`` at call time —
        the pools are reassigned after every donated program, so a
        captured pool reference would go stale."""
        return (np.asarray(self.cache.k[:, block]),
                np.asarray(self.cache.v[:, block]))

    def _kv_scatter_block(self, block: int, k_np, v_np) -> None:
        """Write fetched/migrated K/V bytes into ``block`` on device
        (the promotion path back into HBM)."""
        import jax.numpy as jnp
        self.cache.k = self.cache.k.at[:, block].set(
            jnp.asarray(k_np, self.cache.dtype))
        self.cache.v = self.cache.v.at[:, block].set(
            jnp.asarray(v_np, self.cache.dtype))

    @property
    def host_link_bps(self) -> float:
        """Host<->device offload-link rate the spill tier is priced
        at — the SAME shared channel the offload-remat policy models
        (one owner in ``cost_model``, no drift)."""
        from ..observability.cost_model import host_link_bps
        return host_link_bps(self.config.host_link_gbps)

    # -- request intake --------------------------------------------------
    def submit(self, prompt: Seq[int], max_new_tokens: int,
               arrival_t: float = 0.0, priority: Optional[int] = None,
               deadline_s: Optional[float] = None,
               trace_id: Optional[int] = None) -> int:
        """Submit one request. Typed rejections at submit time:
        :class:`~.reliability.PromptTooLongError` when the request can
        never fit the model's context,
        :class:`~.reliability.QueueFullError` when the bounded
        admission queue is full and the overload policy finds nothing
        lower-priority to shed. ``priority`` (higher = more important)
        and ``deadline_s`` (relative to ``arrival_t``) default from
        the engine's :class:`~.reliability.ReliabilityConfig`.
        ``trace_id`` is the stable id the request-tracing plane keys
        this request's span tree by (the failover router stamps its
        fleet-global id; default: this engine's request id)."""
        self._check_alive()
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise RequestRejected("empty prompt")
        if max_new_tokens < 1:
            raise RequestRejected(
                "max_new_tokens must be >= 1 (prefill always produces "
                "the first token)")
        if len(prompt) + max_new_tokens > self.max_model_len:
            # typed + at submit time: letting this through would only
            # surface later as a block-coverage stall or a clamped
            # position — far less legible than refusing the request
            raise PromptTooLongError(
                f"prompt({len(prompt)}) + max_new({max_new_tokens}) "
                f"exceeds max_model_len {self.max_model_len}")
        rel = self.scheduler.reliability
        rid = self._next_req_id
        self._next_req_id += 1
        req = Request(rid, prompt, int(max_new_tokens), arrival_t,
                      priority=(rel.default_priority if priority is None
                                else int(priority)),
                      deadline_t=rel.deadline_for(arrival_t, deadline_s),
                      trace_id=(rid if trace_id is None else trace_id))
        seq = Sequence(req, self.allocator)
        self.scheduler.submit(seq)     # may shed, may raise QueueFull
        self._seqs[rid] = seq
        _flight_record(event="submit", req=rid, tid=req.trace_id,
                       t=arrival_t, engine=self.engine_id,
                       prompt_tokens=len(prompt),
                       max_new=int(max_new_tokens))
        self._gauge()
        return rid

    def sequence(self, req_id: int) -> Sequence:
        return self._seqs[req_id]

    # -- failure plane ---------------------------------------------------
    def _check_alive(self) -> None:
        if self.failed:
            raise EngineFailedError(
                f"engine {self.engine_id} failed: {self.fail_reason}")

    def fail(self, reason: str, now: float = 0.0) -> None:
        """Mark this engine dead (idempotent). Device state — pools,
        compiled programs — is considered lost; host state (token
        logs, the scheduler ledger) survives for
        :meth:`recover_inflight`."""
        from ..observability import metrics
        if self.failed:
            return
        self.failed = True
        self.fail_reason = reason
        self.failed_t = now
        metrics.inc("serving_engine_failures_total")
        # the span carries every in-flight trace id: each of those
        # requests' failover_stall starts at THIS stamp (detection
        # latency is part of the stall), and the chaos fault that
        # killed the engine is attributable to specific requests
        tids = [s.trace_id for s in self.scheduler.running()
                + self.scheduler.waiting if s.trace_id is not None]
        _flight_record(event="engine_failed", engine=self.engine_id,
                       reason=reason, t=now, tids=tids or None)

    def recover_inflight(self) -> List[Sequence]:
        """Harvest every unfinished sequence of a FAILED engine for
        adoption elsewhere: running first (admission order — oldest
        work resumes first), then the waiting queue in order. Tables
        are dead with the engine; each sequence's accepted tokens live
        in its host-side token log, and re-prefilling that log
        reproduces the lost KV exactly (the eviction-exactness
        guarantee), so the continuation is token-for-token identical
        to a fault-free run."""
        if not self.failed:
            raise EngineFailedError(
                "recover_inflight is only valid on a failed engine "
                "(a healthy engine's sequences are still being served)")
        running = list(self.scheduler._running)
        waiting = [s for s in self.scheduler.waiting
                   if s.state is SeqState.WAITING]
        self.scheduler._running = []
        self.scheduler.waiting = []
        for s in running:
            # only ever-ADMITTED work counts as a recovery: the
            # recoveries counter feeds _in_flight(), which exempts a
            # sequence from shedding/deadlines on the adopter — a
            # never-admitted waiting request must keep fresh-arrival
            # admission semantics there (its deadline still applies)
            s.state = SeqState.WAITING
            s.recoveries += 1
        return running + waiting

    def adopt(self, seq: Sequence, now: Optional[float] = None) -> int:
        """Adopt a sequence recovered from a dead engine: re-key it
        into this engine's request map and bind a fresh table on this
        engine's allocator (``trace_id`` survives the re-key — spans
        stay joined across the failover). Ever-ADMITTED work (tokens
        accepted) requeues at the FRONT, exempt from the admission
        bound — in-flight is honored. A never-admitted fresh arrival
        keeps fresh-arrival semantics: it goes through the normal
        bounded ``submit`` path, so the adopter's queue depth and shed
        policy still govern it (a refusal marks it SHED with the typed
        error, never silently over-fills the queue). ``now`` stamps
        the adoption span (the router passes its probe time)."""
        from ..observability import metrics
        from .reliability import QueueFullError
        self._check_alive()
        rid = self._next_req_id
        self._next_req_id += 1
        seq.request.req_id = rid
        seq.rebind(self.allocator)
        seq.ready_at = 0.0
        self._seqs[rid] = seq
        if self.scheduler._in_flight(seq):
            self.scheduler.requeue_front(seq, now=now, cause="adopt")
        else:
            try:
                self.scheduler.submit(seq)
            except QueueFullError as e:
                self.scheduler.mark_shed(seq, e, now=now)
        if seq.state is not SeqState.SHED:
            # an adoption the bounded queue refused is a shed (counted
            # by mark_shed), not a recovery
            metrics.inc("serving_recovered_seqs_total")
        _flight_record(event="adopt", engine=self.engine_id, req=rid,
                       tid=seq.trace_id, t=now, tokens=len(seq.tokens),
                       shed=seq.state is SeqState.SHED)
        self._gauge()
        return rid

    # -- weight hot-swap -------------------------------------------------
    def swap_weights(self, weights, now: float = 0.0,
                     source=None) -> List:
        """Swap new checkpoint weights into the running engine between
        decode steps. ``weights`` is a model (``GPTForCausalLM``) or a
        flat array list matching the runner state. Weights-as-args
        means the compiled programs are untouched — the swap can never
        grow the decode program census. Returns the previous weight
        arrays (the rollback payload).

        ``source`` (``CheckpointManager.swap_source()`` shape) stamps
        the producing checkpoint's restart generation onto the
        ``hot_swap`` span — and because that span carries ``t=`` and
        the in-flight ``tids=``, the generation rides into every
        affected request's trace."""
        from ..observability import metrics
        self._check_alive()
        arrays = weights
        if hasattr(weights, "state_dict"):       # a live model
            from ..jit.functional import _collect_state
            params, buffers = _collect_state([weights])
            arrays = [t._data for t in params + buffers]
        prev = self.runner.swap_weights(arrays)
        metrics.inc("serving_hot_swaps_total")
        # weights-as-args means the swap costs the running batch ZERO
        # pause (pause_s stays 0.0); the span still stamps WHICH
        # requests were in flight, so a future engine that must
        # quiesce can price its pause into their swap_stall component
        tids = [s.trace_id for s in self.scheduler.running()
                if s.trace_id is not None]
        src = source or {}
        _flight_record(event="hot_swap", engine=self.engine_id, t=now,
                       tids=tids or None, pause_s=0.0,
                       generation=src.get("generation"),
                       ckpt_step=src.get("step"),
                       session=src.get("session"))
        return prev

    # -- admission + prefill ---------------------------------------------
    def admit_and_prefill(self, now: float = 0.0,
                          ready_at_fn=None) -> List[dict]:
        """One admission round: FIFO-admit within the prefill budget,
        prefill each admitted sequence (ALL its tokens — first
        admission or post-eviction recompute), scatter K/V into its
        blocks, and sample its next token. Returns per-admission info
        dicts (seq, prompt_tokens, padded_len, cost) for the caller's
        clock; ``ready_at_fn(info) -> float`` (default: ``now``)
        stamps when each sequence may join the decode batch — the sim
        sets it to the prefill LANE's completion time, which is the
        whole point of disaggregation: decode never waits on it."""
        from ..observability import metrics
        self._check_alive()
        out = []
        for seq in self.scheduler.admit(now):
            n = len(seq.tokens)
            tok, k_stack, v_stack = self.runner.prefill(seq.tokens)
            row = np.asarray(seq.table.blocks, np.int64)
            # prefix-cache hit: the leading cached positions' KV is
            # ALREADY in the pool (and shared — rewriting it would
            # scribble on every sibling), so only the private tail is
            # scattered. The prefill still computed the full prompt:
            # the tail's hidden states need the prefix context, and
            # the first generated token comes from the last position.
            start = min(seq.prefix_cached_tokens, n)
            self.cache.k = PagedKVCache.scatter_prefill(
                self.cache.k, k_stack, row, n, self.cache.block_size,
                start=start)
            self.cache.v = PagedKVCache.scatter_prefill(
                self.cache.v, v_stack, row, n, self.cache.block_size,
                start=start)
            seq.table.num_tokens = n
            seq.tokens.append(tok)
            padded = self.runner.prefill_padded_len(n)
            cost = self.runner.prefill_cost(padded)
            info = {"seq": seq, "prompt_tokens": n, "padded_len": padded,
                    "cost": cost}
            if self.host_tier is not None and cost and start > 0:
                # tiering charges the clock for the UNCACHED tail only
                # (linear token scaling of the full-prompt cost): the
                # cached prefix's KV already exists, and a real system
                # with paged-context prefill skips its compute. The
                # full prefill still RUNS (exactness — the tail's
                # hidden states need the prefix context); only the
                # modeled charge shrinks. Off-tier engines keep the
                # PR 13 full-charge behavior bitwise.
                # a FULL-prompt hit still computes the last position
                # (the first generated token's logits need it), so the
                # charge floors at one token — never the flopless
                # zero-dict that would trip the clock fallback
                frac = (n - min(start, n - 1)) / n
                info["charged_cost"] = {k: v * frac
                                        for k, v in cost.items()}
            ready = (ready_at_fn(info) if ready_at_fn is not None
                     else now)
            # tier-fetch stall: host-tier promotions pay the shared
            # offload link, peer fetches carry their modeled DCN
            # seconds from the registry's cost decision — both land
            # AFTER the prefill interval so the decomposition's
            # spill_fetch component never overlaps prefill_s
            host_blocks = getattr(seq, "kv_fetched_host", 0)
            peer_blocks = getattr(seq, "kv_fetched_peer", 0)
            fetch_s = (host_blocks * self.cache.block_bytes
                       / self.host_link_bps
                       + getattr(seq, "kv_peer_fetch_s", 0.0))
            seq.ready_at = ready + fetch_s
            if seq.first_token_t is None:
                seq.first_token_t = seq.ready_at
                metrics.observe("serving_ttft_s",
                                max(0.0, seq.first_token_t
                                    - seq.request.arrival_t))
            self.scheduler.mark_running(seq)
            # prefill span: admission -> first-token-ready on the
            # prefill lane (lane queueing included — the decode lane
            # never waits on it). `end` is the EXACT lane stamp so
            # a finish-at-prefill closes the sum bitwise.
            _flight_record(event="prefill", req=seq.req_id,
                           tid=seq.trace_id, t=now, end=ready,
                           engine=self.engine_id, tokens=n,
                           padded=padded)
            if fetch_s:
                _flight_record(event="spill_fetch", req=seq.req_id,
                               tid=seq.trace_id, t=ready,
                               end=seq.ready_at, engine=self.engine_id,
                               host_blocks=host_blocks or None,
                               peer_blocks=peer_blocks or None)
            metrics.inc("serving_prefill_tokens_total", n)
            if seq.done:
                # its only token materializes when the prefill LANE
                # finishes — finishing at the admission instant would
                # stamp finish_t before first_token_t
                self.scheduler.finish(seq, seq.ready_at)
            out.append(info)
        self._gauge()
        return out

    # -- block-table integrity --------------------------------------------
    def _validate_tables(self, active: List[Sequence],
                         now: Optional[float] = None) -> List[Sequence]:
        """Integrity-check every RUNNING sequence's block table before
        the decode step consumes it: ids in the usable range, coverage
        for the cached tokens, and every block claimed no more often
        than its REFCOUNT covers. A repeat WITHIN one table is always
        corruption; a block claimed by several tables is legitimate
        copy-on-write sharing exactly when the claim count (plus the
        prefix cache's own hold) stays within the allocator's
        refcount — a scribble that aliases someone's block overshoots
        it. A violator (chaos ``corrupt_block_table``, a real
        scribble) is requeued for re-prefill from its token log and
        the allocator's free list AND refcounts are rebuilt from the
        SURVIVING claims — the corrupt ids cannot be trusted enough to
        free() (double-free risk); the prefix cache's held blocks are
        one more survivor claim list. Returns the still-running subset
        of ``active``."""
        from ..observability import metrics
        claimants: Dict[int, List[Sequence]] = {}
        bad: List[Sequence] = []
        for s in self.scheduler.running():
            ok = len(s.table.blocks) >= blocks_for_tokens(
                max(s.table.num_tokens, 1), self.config.block_size)
            seen = set()
            for b in s.table.blocks:
                if not (0 < b < self.config.num_blocks):
                    ok = False
                    break
                if b in seen:
                    # a self-dup aliases two of this sequence's own
                    # token pages onto one block — never legitimate
                    ok = False
                    break
                seen.add(b)
                claimants.setdefault(b, []).append(s)
            if not ok:
                bad.append(s)
        held = (set(self.prefix_cache.held_blocks())
                if self.prefix_cache is not None else ())
        for b, owners in claimants.items():
            hold = 1 if b in held else 0
            if len(owners) + hold > self.allocator.refcount(b):
                # over-claimed: sharing must be covered by references.
                # A cross-table alias cannot say WHICH table was
                # scribbled, so every claimant is rebuilt — re-prefill
                # is exact either way.
                for s in owners:
                    if s not in bad:
                        bad.append(s)
        if not bad:
            return active
        for s in bad:
            metrics.inc("serving_table_corruptions_total")
            _flight_record(event="table_corrupt", engine=self.engine_id,
                           req=s.req_id, tid=s.trace_id, t=now,
                           blocks=list(s.table.blocks))
            self.scheduler.requeue_corrupt(s, now=now)
        survivors = [s.table.blocks for s in self.scheduler.running()]
        if self.prefix_cache is not None:
            survivors.append(self.prefix_cache.held_blocks())
        self.allocator.rebuild_free_list(survivors)
        return [s for s in active if s.state is SeqState.RUNNING]

    # -- one decode step -------------------------------------------------
    def decode_once(self, now: float = 0.0) -> Optional[dict]:
        """Run ONE compiled decode step over every running sequence
        whose prefill has completed (``ready_at <= now``). Returns a
        step info dict, or None when nothing is ready. Raises
        :class:`~.reliability.EngineFailedError` when the engine is
        (or chaos makes it) dead."""
        from ..distributed.fault_tolerance import chaos
        from ..observability import metrics
        self._check_alive()
        active = [s for s in self.scheduler.running()
                  if getattr(s, "ready_at", 0.0) <= now]
        if not active:
            return None
        # chaos scribbles land BEFORE validation — the validator must
        # catch them like any organic corruption (the active() guard
        # keeps the disarmed path free of the list allocation)
        if chaos.active() is not None:
            chaos.maybe_corrupt_block_table(
                [s.table.blocks for s in active])
            if self.host_tier is not None:
                chaos.maybe_corrupt_spill_block(self.host_tier)
        active = self._validate_tables(active, now=now)
        if not active:
            return None
        victims = self.scheduler.reserve_decode_slots(active, now=now)
        if victims:
            # counted HERE, not after the step: evicting every ready
            # sequence aborts the step below, and those evictions must
            # not vanish from the counter
            metrics.inc("serving_evictions_total", len(victims))
        active = [s for s in active if s.state is SeqState.RUNNING]
        if not active:
            return None
        if chaos.maybe_kill_engine(self.engine_id, self.decode_steps + 1):
            self.fail("chaos:kill_engine", now=now)
            raise EngineFailedError(
                f"engine {self.engine_id} killed by chaos at decode "
                f"step {self.decode_steps + 1}")
        cfg = self.scheduler.config
        # -- speculative drafts (host, deterministic): each sequence
        # may contribute 1 + k chunk rows to this round's verify batch.
        # spec=None degenerates to EXACTLY the PR 9 single-row step —
        # same buckets, same arrays, same program.
        spec = self.config.spec
        drafts: Dict[int, List[int]] = {}
        if spec is not None:
            for s in active:
                room = s.request.max_new_tokens - len(s.generated)
                k = min(spec.num_draft_tokens, room - 1)
                if k < 1:
                    continue
                d = (spec.draft_fn(s) if spec.draft_fn is not None
                     else ngram_draft(s.tokens, spec.ngram, k))
                d = [int(t) for t in d][:k]
                if d:
                    drafts[id(s)] = d
        if drafts:
            # verify rows need their slots reserved UP FRONT (the
            # program scatters the whole chunk's KV); rejected tails
            # roll back via truncate below
            slots = [1 + len(drafts.get(id(s), ())) for s in active]
            spec_victims = self.scheduler.reserve_decode_slots(
                active, now=now, slots=slots)
            if spec_victims:
                metrics.inc("serving_evictions_total",
                            len(spec_victims))
                victims += spec_victims
                active = [s for s in active
                          if s.state is SeqState.RUNNING]
                drafts = {k: v for k, v in drafts.items()
                          if k in {id(s) for s in active}}
            if not active:
                return None
        rows = []                      # (seq, token, position)
        for s in active:
            p0 = s.num_cached
            rows.append((s, s.tokens[p0], p0))
            for i, d in enumerate(drafts.get(id(s), ())):
                rows.append((s, d, p0 + 1 + i))
        b_bucket = cfg.batch_bucket(len(rows))
        p_bucket = self.scheduler.decode_bucket(active)[1]
        ids = np.zeros((b_bucket, 1), np.int32)
        positions = np.zeros((b_bucket,), np.int32)
        tables = np.full((b_bucket, p_bucket), GARBAGE_BLOCK, np.int32)
        for i, (s, tok_in, pos) in enumerate(rows):
            ids[i, 0] = tok_in
            positions[i] = pos
            tables[i] = s.table.padded(p_bucket)
        with metrics.phase("compute"):
            toks = self.runner.decode(self.cache, ids, positions, tables)
        cost = self.runner.decode_cost((b_bucket, p_bucket))
        modeled_s = None
        if cost and "flops" in cost:
            from ..observability.cost_model import StepCost
            sc = StepCost(flops=cost.get("flops", 0.0),
                          hbm_bytes=cost.get("bytes accessed", 0.0))
            modeled_s = sc.step_time_modeled_s()
        # per-step span for the whole batch: each covered request's
        # decode_compute grows by the modeled step cost — the SAME
        # float the finish stamp below is built from, so the interval
        # end and a final-step finish quantize identically
        step_tids = [s.trace_id for s in active
                     if s.trace_id is not None]
        if chaos.maybe_drop_decode_step(self.engine_id):
            # transient step failure: the tokens are discarded and NO
            # sequence state advances, so the next step recomputes the
            # same positions (same inputs -> same tokens; the KV
            # rewrite is idempotent; the drafts are a pure function of
            # the unchanged token log) — retry costs one modeled step
            metrics.inc("serving_retries_total")
            _flight_record(event="decode_step_dropped",
                           engine=self.engine_id, t=now,
                           dur=modeled_s or 0.0,
                           tids=step_tids or None,
                           chaos="drop_decode_step",
                           step=self.decode_steps + 1)
            self.decode_steps += 1
            return {"bucket": (b_bucket, p_bucket),
                    "n_active": len(active), "tokens": 0,
                    "evictions": len(victims), "dropped": True,
                    "cost": cost}
        # tokens exist at the step's END: finishing at `now` would cut
        # the final step's cost out of the virtual-clock makespan and
        # overstate the benched tokens/s
        done_at = now + (modeled_s or 0.0)
        self.decode_steps += 1
        _flight_record(event="decode_step", engine=self.engine_id,
                       t=now, dur=modeled_s or 0.0,
                       tids=step_tids or None,
                       step=self.decode_steps, batch=len(active),
                       rows=len(rows) if drafts else None,
                       bucket=[b_bucket, p_bucket])
        emitted_total = 0
        accepted_total = 0
        rejected_total = 0
        ri = 0
        for s in active:
            n_rows = 1 + len(drafts.get(id(s), ()))
            outs = [int(toks[ri + j]) for j in range(n_rows)]
            ri += n_rows
            if n_rows == 1:
                emitted = [outs[0]]
            else:
                room = s.request.max_new_tokens - len(s.generated)
                accepted, bonus = accept_drafts(drafts[id(s)], outs,
                                                room)
                emitted = accepted + [bonus]
                accepted_total += len(accepted)
                rejected_total += len(drafts[id(s)]) - len(accepted)
            for tok in emitted:
                s.table.append_slot()
                s.tokens.append(tok)
            if n_rows > 1:
                # rejected tail: its KV writes sit past num_tokens and
                # are overwritten before any read; surplus blocks roll
                # back to the allocator here
                s.table.truncate()
            emitted_total += len(emitted)
            if s.done:
                self.scheduler.finish(s, done_at)
        if accepted_total:
            metrics.inc("serving_spec_accepted_total", accepted_total)
            self.spec_accepted += accepted_total
        if rejected_total:
            metrics.inc("serving_spec_rejected_total", rejected_total)
            self.spec_rejected += rejected_total
        info = {"bucket": (b_bucket, p_bucket), "n_active": len(active),
                "tokens": emitted_total, "evictions": len(victims),
                "spec_accepted": accepted_total,
                "spec_rejected": rejected_total,
                "cost": cost}
        metrics.inc("serving_decode_tokens_total", emitted_total)
        self._gauge()
        extra = {"serving": 1,
                 "batch_occupancy": len(active) / cfg.max_batch}
        if modeled_s is not None:
            extra["modeled_step_s"] = modeled_s
        metrics.step_end(tokens=emitted_total, **extra)
        return info

    def tick(self, now: float = 0.0) -> Optional[dict]:
        """Convenience round for live serving: admissions then one
        decode step, both stamped with ``now``."""
        self.admit_and_prefill(now)
        return self.decode_once(now)

    # -- reporting -------------------------------------------------------
    def _gauge(self) -> None:
        from ..observability import metrics
        metrics.set_gauge("serving_queue_depth",
                          self.scheduler.queue_depth)
        metrics.set_gauge("serving_batch_occupancy",
                          len(self.scheduler.running())
                          / self.scheduler.config.max_batch)
        metrics.set_gauge("serving_kv_blocks_in_use",
                          self.allocator.used_count)
        metrics.set_gauge("serving_kv_blocks_high_water",
                          self.allocator.high_water)
        metrics.set_gauge("serving_decode_programs",
                          self.runner.num_decode_programs)
        if self.prefix_cache is not None:
            metrics.set_gauge(
                "serving_shared_kv_bytes",
                self.prefix_cache.shared_bytes(self.cache.block_bytes))
            metrics.set_gauge("serving_prefix_cached_blocks",
                              len(self.prefix_cache))
            self._flush_kv_counters()
        if self.host_tier is not None:
            metrics.set_gauge("serving_kv_host_tier_blocks",
                              len(self.host_tier))
            metrics.set_gauge("serving_kv_host_tier_bytes",
                              len(self.host_tier)
                              * self.cache.block_bytes)

    def _flush_kv_counters(self) -> None:
        """Emit KV-tier counter DELTAS into the metrics plane. Spills
        and fetches fire deep inside the allocator's reclaim hook and
        the cache's lookup, so the engine reconciles the cache's
        monotonic totals here (every _gauge call) instead of threading
        the metrics plane through the block layer."""
        from ..observability import metrics
        pc = self.prefix_cache
        totals = (("serving_kv_spill_blocks_total", pc.spills),
                  ("serving_kv_fetch_host_blocks_total", pc.host_fetches),
                  ("serving_kv_fetch_peer_blocks_total", pc.peer_fetches))
        for name, total in totals:
            delta = total - self._kv_counts.get(name, 0)
            if delta:
                metrics.inc(name, delta)
                self._kv_counts[name] = total

    @property
    def num_decode_programs(self) -> int:
        return self.runner.num_decode_programs

    @property
    def program_budget(self) -> int:
        return self.scheduler.config.program_budget

    def kv_high_water_bytes(self) -> int:
        return self.cache.bytes_for_blocks(self.allocator.high_water)

    def contiguous_cache_bytes(self) -> int:
        """The comparator: a contiguous per-slot max-seq-len cache for
        the full decode batch."""
        return self.cache.contiguous_bytes(self.config.max_batch,
                                           self.max_model_len)

    def idle(self) -> bool:
        return not self.scheduler.waiting and not self.scheduler.running()
