"""Compiled prefill/decode programs for GPT-family models over the
paged KV cache.

The decode step cannot reuse ``GPTModel.decode_step`` (whose KV cache
is a growing per-layer concat — exactly the contiguous layout paging
replaces), so this runner re-wires one block step from the model's OWN
sublayers (ln_1 -> fused qkv -> paged append -> paged attention ->
out_proj -> mlp), mirroring ``GPTBlock.forward``'s head-major qkv
split. Prefill DOES go through ``decode_step`` (empty caches): it
computes every prompt position's K/V in one causal pass, and the
runner scatters them into the sequence's blocks.

Both paths are pure functions compiled with ``jax.jit``:

* weights ride as ARGUMENTS (the ``TracedProgram``/``_export_program``
  param-swap pattern) — never baked in as constants;
* the decode program is keyed by the scheduler's (batch, pages)
  bucket, so the program count is bounded by the bucket grid (the
  bench gate), and DONATES the KV pools for in-place append;
* prefill is keyed by the padded prompt length (rounded up to
  :data:`PREFILL_PAD`); causal masking makes the padded tail invisible
  to real rows, so padding is exact, and the real last position is a
  runtime index.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .paged_attention import paged_attention_decode

__all__ = ["PagedGPTRunner", "PREFILL_PAD"]

# prefill programs are compiled per padded length; 16-token rounding
# bounds their count at max_model_len/16 without wasting much compute
PREFILL_PAD = 16


class PagedGPTRunner:
    """Owns the compiled programs + the state plumbing for one
    ``GPTForCausalLM``. Greedy (argmax) decoding — sampling belongs to
    a later PR; greedy is what the eviction-exactness guarantee is
    stated for."""

    def __init__(self, model, num_heads: int, head_dim: int,
                 interpret: Optional[bool] = None,
                 split_pages: Optional[int] = None):
        from ..jit.functional import _collect_state
        self.model = model
        model.eval()
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.interpret = interpret
        # split-K width for the paged-attention kernel (None = the
        # kernel's VMEM-fit auto dispatch); rides into every compiled
        # decode program
        self.split_pages = split_pages
        params, buffers = _collect_state([model])
        self._state = params + buffers
        # hot-swap overlay: when set, these arrays (NOT the live model
        # tensors) ride as the programs' weight arguments — per-runner,
        # so engines sharing one model object swap independently
        self._swap_arrays: Optional[List] = None
        self._decode_programs: Dict[Tuple[int, int], object] = {}
        self._prefill_programs: Dict[int, object] = {}
        self._decode_costs: Dict[Tuple[int, int], Optional[dict]] = {}
        self._prefill_costs: Dict[int, Optional[dict]] = {}

    # -- state plumbing --------------------------------------------------
    def _weights(self) -> List:
        if self._swap_arrays is not None:
            return list(self._swap_arrays)
        return [t._data for t in self._state]

    def swap_weights(self, arrays) -> List:
        """Live weight hot-swap: replace the arrays every compiled
        program receives as its weight ARGUMENTS. Because weights ride
        as arguments (the ``TracedProgram`` pattern), a swap between
        decode steps is just different operands to the SAME compiled
        programs — no recompile, so the decode program census cannot
        grow (the zero-extra-programs half of the hot-swap gate).

        ``arrays`` must match the model state leaf-for-leaf (length,
        shape, dtype); any mismatch raises
        :class:`~.reliability.WeightSwapError` BEFORE anything is
        applied — a swap is atomic. Returns the previous weight list
        (the rollback payload)."""
        import jax.numpy as jnp
        from .reliability import WeightSwapError
        arrays = list(arrays)
        if len(arrays) != len(self._state):
            raise WeightSwapError(
                f"swap payload has {len(arrays)} leaves, model has "
                f"{len(self._state)}")
        staged = []
        for t, a in zip(self._state, arrays):
            a = jnp.asarray(a)
            if tuple(a.shape) != tuple(t._data.shape) \
                    or a.dtype != t._data.dtype:
                raise WeightSwapError(
                    f"swap leaf mismatch: got {a.shape}/{a.dtype}, "
                    f"model has {tuple(t._data.shape)}/{t._data.dtype}")
            staged.append(a)
        prev = self._weights()
        self._swap_arrays = staged
        return prev

    def _swapped(self, weight_arrays):
        """Context manager: point every model param/buffer at the
        traced arrays for the duration of a pure-function body."""
        runner = self

        class _Swap:
            def __enter__(self):
                self._orig = [t._data for t in runner._state]
                for t, a in zip(runner._state, weight_arrays):
                    t._data = a

            def __exit__(self, *exc):
                for t, a in zip(runner._state, self._orig):
                    t._data = a
                return False

        return _Swap()

    @property
    def num_decode_programs(self) -> int:
        return len(self._decode_programs)

    # -- prefill ---------------------------------------------------------
    @staticmethod
    def pad_len(n: int, max_pos: int) -> int:
        padded = -(-n // PREFILL_PAD) * PREFILL_PAD
        return min(padded, max_pos) if n <= max_pos else n

    def prefill_padded_len(self, n: int) -> int:
        """The padded length ``prefill`` will key its program/cost by —
        the ONE authoritative key (callers must not re-derive it with a
        different ceiling, or cost lookups silently miss)."""
        return self.pad_len(n, self.model.cfg.max_position_embeddings)

    def _build_prefill(self, padded_len: int):
        import jax
        import jax.numpy as jnp
        from ..framework import core
        from ..framework import random as fr
        from ..framework.tensor import Tensor
        model = self.model

        def pure_prefill(weight_arrays, ids, last_idx):
            # ids: [1, padded_len] int32; last_idx: int32 scalar index
            # of the real last token (causal masking makes the padded
            # tail invisible to every real row)
            with self._swapped(weight_arrays), core.no_grad(), \
                    fr.scoped_rng(jax.random.PRNGKey(0)):
                n_layers = model.cfg.num_layers
                hidden, caches = model.gpt.decode_step(
                    Tensor(ids), [() for _ in range(n_layers)], 0)
                h_last = jnp.take_along_axis(
                    hidden._data, last_idx.reshape(1, 1, 1), axis=1)
                logits = model._head(Tensor(h_last))
            tok = jnp.argmax(logits._data[:, -1], axis=-1).astype(jnp.int32)
            k_stack = jnp.stack([c[0]._data[0] for c in caches])
            v_stack = jnp.stack([c[1]._data[0] for c in caches])
            return tok, k_stack, v_stack        # [L, padded_len, H, D]

        return jax.jit(pure_prefill)

    def prefill(self, token_ids: List[int]):
        """Run one sequence's prompt; returns (first_token:int,
        k_stack, v_stack) with stacks ``[L, padded_len, H, D]`` — the
        caller scatters rows ``[:len(token_ids)]`` into blocks."""
        import jax.numpy as jnp
        n = len(token_ids)
        padded = self.prefill_padded_len(n)
        fn = self._prefill_programs.get(padded)
        if fn is None:
            fn = self._build_prefill(padded)
            self._prefill_programs[padded] = fn
        ids = np.zeros((1, padded), np.int32)
        ids[0, :n] = token_ids
        tok, k_stack, v_stack = fn(self._weights(), jnp.asarray(ids),
                                   jnp.asarray(n - 1, jnp.int32))
        if padded not in self._prefill_costs:
            self._prefill_costs[padded] = self._cost_of(
                fn, (self._weights(), jnp.asarray(ids),
                     jnp.asarray(n - 1, jnp.int32)))
        return int(tok[0]), k_stack, v_stack

    # -- decode ----------------------------------------------------------
    def _build_decode(self, batch: int, n_pages: int, block_size: int):
        import jax
        import jax.numpy as jnp
        from ..framework import core
        from ..framework import random as fr
        from ..framework.tensor import Tensor
        model = self.model
        nh, hd = self.num_heads, self.head_dim

        def pure_decode(weight_arrays, k_pool, v_pool, ids, positions,
                        block_tables):
            # ids [B,1] int32; positions [B] int32 (0-based slot of the
            # NEW token); block_tables [B,P] int32. Pools
            # [L, N, bs, H, D], donated.
            B = batch
            phys = jnp.take_along_axis(
                block_tables, (positions // block_size)[:, None],
                axis=1)[:, 0]
            slot = positions % block_size
            ctx = positions + 1
            with self._swapped(weight_arrays), core.no_grad(), \
                    fr.scoped_rng(jax.random.PRNGKey(0)):
                pos_t = Tensor(positions[:, None].astype(jnp.int32))
                x = model.gpt.wte(Tensor(ids)) + model.gpt.wpe(pos_t)
                for li, block in enumerate(model.gpt.h):
                    ln1 = block.ln_1(x)
                    qkv = block.attn.qkv(ln1)
                    # head-major fused split, as GPTAttention.forward
                    qkv = qkv.reshape([B, 1, nh, 3, hd])
                    q, k, v = qkv.unbind(axis=3)
                    from .block_cache import PagedKVCache as _C
                    k_pool = _C.scatter_decode(k_pool, li, phys, slot,
                                               k._data[:, 0])
                    v_pool = _C.scatter_decode(v_pool, li, phys, slot,
                                               v._data[:, 0])
                    attn = paged_attention_decode(
                        q._data, k_pool[li], v_pool[li], block_tables,
                        ctx, interpret=self.interpret,
                        pages_per_split=self.split_pages)
                    a = block.attn.out_proj(
                        Tensor(attn.reshape(B, 1, nh * hd)))
                    x = x + block.dropout(a)
                    x = x + block.dropout(block.mlp(block.ln_2(x)))
                x = model.gpt.ln_f(x)
                logits = model._head(x)
            tok = jnp.argmax(logits._data[:, -1], axis=-1).astype(jnp.int32)
            return tok, k_pool, v_pool

        return jax.jit(pure_decode, donate_argnums=(1, 2))

    def decode(self, cache, ids, positions, block_tables):
        """One decode step over a bucketed batch. ``cache`` is the
        :class:`~.block_cache.PagedKVCache` whose pools are donated
        and replaced. Returns int32 next tokens ``[B]``."""
        import jax.numpy as jnp
        B, n_pages = block_tables.shape
        key = (B, n_pages)
        fn = self._decode_programs.get(key)
        if fn is None:
            fn = self._build_decode(B, n_pages, cache.block_size)
            self._decode_programs[key] = fn
        args = (self._weights(), cache.k, cache.v,
                jnp.asarray(ids, jnp.int32),
                jnp.asarray(positions, jnp.int32),
                jnp.asarray(block_tables, jnp.int32))
        if key not in self._decode_costs:
            self._decode_costs[key] = self._cost_of(fn, args)
        tok, cache.k, cache.v = fn(*args)
        return np.asarray(tok)

    # -- deterministic cost accounting (PR 7 cost model) -----------------
    @staticmethod
    def _cost_of(fn, args) -> Optional[dict]:
        from ..observability.cost_model import abstractify, program_cost
        return program_cost(fn, abstractify(args))

    def decode_cost(self, bucket: Tuple[int, int]) -> Optional[dict]:
        return self._decode_costs.get(tuple(bucket))

    def prefill_cost(self, padded_len: int) -> Optional[dict]:
        return self._prefill_costs.get(int(padded_len))
