"""Pallas paged-attention decode kernel + dense reference paths.

The decode-side half of PagedAttention (Kwon et al. SOSP'23) on the
flash kernel's machinery (``kernels/pallas_flash.py``): at decode each
sequence contributes ONE query token and attends over its whole cached
prefix, whose K/V live scattered across fixed-size blocks of the
shared pool (``serving/block_cache.py``). The kernel walks the
sequence's block table — scalar-prefetched so the index maps can
compute DMA source blocks before the body runs (the
``PrefetchScalarGridSpec`` pattern from the official TPU paged
kernels) — and gathers K/V blocks into VMEM.

Two bodies behind ONE dispatcher (:func:`paged_attention_decode`):

* **Single-split (global softmax)** — the PR 9 body: per-page score
  dots write into one ``[8, n_pages*block_size]`` score buffer and
  the softmax runs ONCE over the full row. Numerics contract (the
  serving acceptance gate): bitwise identical in fp32 to
  :func:`paged_attention_reference` (dense gather through the same
  table) which in turn is bitwise identical to
  ``nn.functional.flash_attention`` on the contiguously gathered K/V —
  all three run the *same op sequence*: ``dot(q, k) * scale`` -> mask
  with ``finfo.min`` -> ``jax.nn.softmax(f32)`` -> ``dot(p, v)``, the
  exact arithmetic of ``kernels/attention._sdpa_xla``. Pad slots hold
  ``finfo.min`` scores (exactly-0.0 probability), and context lengths
  are kept multiples of 8 so padded-width reductions group lanes
  identically. VMEM scales with the context: scores ``8 x S`` + V
  ``S x D`` — ~1.1 MB at S 2048 / D 128 f32, but ~17.8 MB at S 32768 /
  D 128, PAST the ~16 MB/core budget: this body cannot serve 32k
  contexts, which is exactly what the split body exists for.

* **Split-K flash-decode (online softmax)** — ISSUE 14 / ROADMAP
  item 4: the context is carved into splits of ``pages_per_split``
  pages; each split runs the flash recurrence epilogue over its own
  bounded score row (running max ``m``, denominator ``l``, and the
  UNNORMALIZED value accumulator ``o`` — the ``pallas_flash.py``
  pattern) and emits ``(m_i, l_i, o_i)`` partials; a tiny cross-split
  reduction (:func:`_merge_splits`, jitted XLA) rescales by
  ``exp(m_i - max m)`` and normalizes once. VMEM is bounded by the
  SPLIT, not the context — any context length fits — and the splits
  are independent (flash-decode parallelism on real hardware; the
  in-kernel grid runs them sequentially per core). Acceptance:
  bitwise (fp32) == :func:`paged_attention_split_reference` (the
  dense twin that mirrors the split body's op sequence one-for-one),
  allclose (1-ulp class) vs the global-softmax reference — the
  per-split rescaling legally reassociates the reductions, so
  bitwise-vs-global is not claimable, which is why SHORT contexts
  keep dispatching to the single-split body and its stricter chain.

Dispatch: ``pages_per_split=None`` (the default) picks the
single-split body whenever its scratch fits the VMEM budget —
bitwise-identical behavior to PR 9 at every context the PR 9 kernel
could serve — and falls over to split-K with an auto-halved split
width beyond it (:func:`auto_pages_per_split`). The deterministic
accounting (:func:`decode_scratch_vmem_bytes`,
:func:`modeled_decode_latency_s`) is what ``bench.py
--serving-throughput`` gates the 32k story on.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..kernels.pallas_flash import NEG_INF, _interpret_default

# jax renamed TPUCompilerParams -> CompilerParams; support both so the
# kernel loads on every jax this repo meets
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

__all__ = ["paged_attention_decode", "paged_attention_reference",
           "paged_attention_split_reference", "gathered_dense_kv",
           "decode_scratch_vmem_bytes", "fits_single_softmax",
           "auto_pages_per_split", "modeled_decode_latency_s",
           "VMEM_BYTES", "VMEM_FIT_BUDGET"]

# v5e-class VMEM per core (the pallas guide's ~16 MB/core figure) and
# the fraction a decode body may claim for its score/value scratch —
# q/k/v tiles, the compiler's own spills, and double-buffering share
# the rest. Both are accounting constants (deterministic on every
# host), not runtime probes.
VMEM_BYTES = 16 * 2 ** 20
VMEM_FIT_BUDGET = VMEM_BYTES // 2


def _precision(dtype):
    # mirror ops.linalg._mxu_precision: bf16/f16 pinned to DEFAULT so
    # the MXU keeps its native-rate path; f32 inherits the global
    # setting — the same choice _sdpa_xla makes, which the bitwise
    # contract depends on
    if jnp.dtype(dtype) in (jnp.bfloat16, jnp.float16):
        return jax.lax.Precision.DEFAULT
    return None


def _decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   s_buf, v_buf, *, scale, block_size, n_pages):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        # dead/pad slots: finfo.min scores (exactly-0 probability after
        # the f32 softmax) and zero V
        s_buf[:] = jnp.full_like(s_buf, jnp.finfo(s_buf.dtype).min)
        v_buf[:] = jnp.zeros_like(v_buf)

    ctx = len_ref[b]

    @pl.when(j * block_size < ctx)
    def _gather():
        # the score dot runs on a SINGLE query row: the gemm's row
        # count changes XLA's reduction grouping (an 8-row dot drifts
        # ~1 ulp from the 1-row dot flash_attention's decode einsum
        # collapses to), and the bitwise contract hinges on matching
        # it exactly. The tile itself stays 8 rows for TPU sublane
        # layout; rows 1..7 are dead weight.
        q = q_ref[0, 0][:1]                   # (1, D) native dtype
        k = k_ref[0, :, 0, :]                 # (bs, D)
        v = v_ref[0, :, 0, :]                 # (bs, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            precision=_precision(q.dtype)) * scale
        cols = jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1) + j * block_size
        s = jnp.where(cols < ctx, s, jnp.finfo(s.dtype).min)
        s_buf[:1, pl.ds(j * block_size, block_size)] = \
            s.astype(s_buf.dtype)
        v_buf[pl.ds(j * block_size, block_size), :] = v

    @pl.when(j == n_pages - 1)
    def _finalize():
        # ONE global softmax over the assembled row — the same
        # softmax(f32)-then-matmul sequence as _sdpa_xla, NOT the
        # online-softmax recurrence (whose per-block rescaling would
        # round differently and break the bitwise contract)
        probs = jax.nn.softmax(
            s_buf[:1].astype(jnp.float32), axis=-1).astype(o_ref.dtype)
        o = jax.lax.dot_general(
            probs, v_buf[:], (((1,), (0,)), ((), ())),
            precision=_precision(o_ref.dtype))       # (1, D)
        o_ref[0, 0] = jnp.broadcast_to(o, o_ref.shape[2:]) \
            .astype(o_ref.dtype)


# ------------------------------------------------- VMEM / cost accounting
def decode_scratch_vmem_bytes(ctx_pad: int, head_dim: int,
                              dtype="float32") -> int:
    """VMEM scratch bytes a SINGLE-SPLIT decode body needs for a
    padded context of ``ctx_pad`` keys: the ``[8, S]`` score buffer
    plus the ``[S, D]`` gathered-V buffer (scores ride at f32 in the
    split body; this accounting uses the wider of score/input dtype so
    the figure upper-bounds both bodies)."""
    it = max(jnp.dtype(dtype).itemsize, 4)
    return (8 * ctx_pad + ctx_pad * head_dim) * it


def fits_single_softmax(n_pages: int, block_size: int, head_dim: int,
                        dtype="float32",
                        budget: int = None) -> bool:
    """Can the PR 9 global-softmax body serve this context at all?
    False at 32k (D 128): its whole-context scratch blows the VMEM
    budget — the feasibility half of the bench's 32k gate."""
    if budget is None:
        budget = VMEM_FIT_BUDGET
    return decode_scratch_vmem_bytes(n_pages * block_size, head_dim,
                                     dtype) <= budget


def auto_pages_per_split(n_pages: int, block_size: int, head_dim: int,
                         dtype="float32",
                         budget: int = None) -> int:
    """Largest halving of ``n_pages`` whose per-split scratch fits the
    VMEM budget (deterministic — no device probing)."""
    pps = max(int(n_pages), 1)
    while pps > 1 and not fits_single_softmax(pps, block_size, head_dim,
                                              dtype, budget):
        pps = -(-pps // 2)
    return pps


def modeled_decode_latency_s(ctx_tokens: int, num_heads: int,
                             head_dim: int, batch: int = 1,
                             dtype="float32", block_size: int = 16,
                             pages_per_split=None,
                             peak_flops=None, hbm_bps=None) -> dict:
    """Deterministic cost x rate model of one paged-attention decode
    step (attention only — the projections are priced by the runner's
    program costs): HBM traffic = K+V streamed once plus, for a split
    kernel, the ``(o, m, l)`` partials' round-trip; FLOPs = the two
    row dots per (batch, head). Returns the modeled seconds next to a
    ``feasible`` verdict from the VMEM accounting — a body whose
    scratch cannot fit has NO latency to model, which is how the PR 9
    kernel fails the 32k gate."""
    from ..observability.cost_model import chip_peak
    if peak_flops is None or hbm_bps is None:
        p, h, _ = chip_peak()
        peak_flops = peak_flops if peak_flops is not None else p
        hbm_bps = hbm_bps if hbm_bps is not None else h
    it = jnp.dtype(dtype).itemsize
    n_pages = -(-int(ctx_tokens) // int(block_size))
    if pages_per_split is None:
        pps = n_pages
    else:
        pps = min(int(pages_per_split), n_pages)
    n_splits = -(-n_pages // pps)
    feasible = fits_single_softmax(pps, block_size, head_dim, dtype)
    kv_bytes = 2.0 * ctx_tokens * num_heads * head_dim * it * batch
    # split partials: o [S, D] f32 + m/l scalars per (b, h), written
    # then re-read by the merge
    part_bytes = (2.0 * batch * num_heads * n_splits * (head_dim + 2)
                  * 4 if n_splits > 1 else 0.0)
    flops = 2.0 * 2.0 * ctx_tokens * num_heads * head_dim * batch
    latency = max(flops / peak_flops, (kv_bytes + part_bytes) / hbm_bps)
    return {"feasible": feasible, "latency_s": latency,
            "kv_bytes": kv_bytes, "partial_bytes": part_bytes,
            "flops": flops, "n_splits": n_splits,
            "pages_per_split": pps,
            "scratch_vmem_bytes": decode_scratch_vmem_bytes(
                pps * block_size, head_dim, dtype)}


# ------------------------------------------- split-K flash-decode body
def _decode_kernel_split(bt_ref, len_ref, q_ref, k_ref, v_ref,
                         o_ref, m_ref, l_ref, s_buf, v_buf, *,
                         scale, block_size, pages_per_split, n_pages):
    """One (batch, head, split) program: gather the split's pages,
    then the flash epilogue over the split's bounded score row —
    ``m_i = max``, ``p = exp(s - m_i)``, ``l_i = sum p``,
    ``o_i = p @ V`` (UNNORMALIZED) — written out as partials for the
    cross-split merge. A fully-dead split (every page past the
    context) emits ``m = -inf, l = 0, o = 0`` and the merge drops it.
    """
    b = pl.program_id(0)
    sp = pl.program_id(2)
    j = pl.program_id(3)                 # page within this split
    jg = sp * pages_per_split + j        # global page index

    @pl.when(j == 0)
    def _init():
        s_buf[:] = jnp.full_like(s_buf, NEG_INF)
        v_buf[:] = jnp.zeros_like(v_buf)

    ctx = len_ref[b]

    @pl.when((jg * block_size < ctx) & (jg < n_pages))
    def _gather():
        # single query row, same discipline as the global body: the
        # per-row dot's reduction grouping is what the bitwise
        # contract vs the split reference is stated over
        q = q_ref[0, 0][:1]                   # (1, D) native dtype
        k = k_ref[0, :, 0, :]                 # (bs, D)
        v = v_ref[0, :, 0, :]                 # (bs, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            precision=_precision(q.dtype)) * scale
        cols = jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1) + jg * block_size
        s = jnp.where(cols < ctx, s.astype(jnp.float32), NEG_INF)
        s_buf[:1, pl.ds(j * block_size, block_size)] = s
        v_buf[pl.ds(j * block_size, block_size), :] = v

    @pl.when(j == pages_per_split - 1)
    def _partial():
        s = s_buf[:1]                               # (1, S_split) f32
        m = jnp.max(s, axis=1, keepdims=True)       # -inf when dead
        safe_m = jnp.where(m == NEG_INF, 0.0, m)
        p = jnp.exp(s - safe_m)
        p = jnp.where(s == NEG_INF, 0.0, p)
        l = jnp.sum(p, axis=1, keepdims=True)
        o = jax.lax.dot_general(
            p.astype(v_buf.dtype), v_buf[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # (1, D) f32
        o_ref[0, 0, 0] = jnp.broadcast_to(o, o_ref.shape[3:])
        m_ref[0, 0, 0] = jnp.broadcast_to(m, m_ref.shape[3:])
        l_ref[0, 0, 0] = jnp.broadcast_to(l, l_ref.shape[3:])


def _merge_splits(o_parts, m, l, out_dtype):
    """Cross-split reduction (f32): rescale every split's partial by
    ``exp(m_i - max m)``, sum, normalize once. ``o_parts``
    ``[B, H, S, D]``; ``m``/``l`` ``[B, H, S]``."""
    m_max = jnp.max(m, axis=2, keepdims=True)           # (B, H, 1)
    safe = jnp.where(m_max == NEG_INF, 0.0, m_max)
    w = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - safe))  # (B, H, S)
    l_tot = jnp.sum(w * l, axis=2)                       # (B, H)
    o = jnp.sum(w[..., None] * o_parts, axis=2)          # (B, H, D)
    l_safe = jnp.where(l_tot == 0.0, 1.0, l_tot)
    return (o / l_safe[..., None]).astype(out_dtype)


def paged_attention_decode(q, k_pool, v_pool, block_tables, ctx_lens,
                           scale=None, interpret=None,
                           pages_per_split=None):
    """Paged decode attention.

    q: ``[B, 1, H, D]`` (paddle layout) — one new token per sequence.
    k_pool/v_pool: ``[num_blocks, block_size, H, D]`` shared pools.
    block_tables: int32 ``[B, n_pages]`` physical block ids per
    sequence (pad rows with the garbage block).
    ctx_lens: int32 ``[B]`` valid keys per sequence (including the
    token just appended). Returns ``[B, 1, H, D]``.

    ``pages_per_split``: split-K width for the flash-decode body.
    ``None`` auto-dispatches — the PR 9 single-split global-softmax
    body (and its bitwise chain) whenever its whole-context scratch
    fits the VMEM budget, else :func:`auto_pages_per_split`. An
    explicit value forces split-K whenever more than one split
    results.
    """
    B, _, H, D = q.shape
    n_blocks, bs, _, _ = k_pool.shape
    n_pages = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if interpret is None:
        interpret = _interpret_default()
    if pages_per_split is None:
        pps = (n_pages if fits_single_softmax(n_pages, bs, D, q.dtype)
               else auto_pages_per_split(n_pages, bs, D, q.dtype))
    else:
        pps = max(1, min(int(pages_per_split), n_pages))
    # q rides as [B, H, 8, D]: 8 identical rows satisfy the TPU
    # sublane-tiling minimum; row 0 is the answer
    qr = jnp.broadcast_to(jnp.swapaxes(q, 1, 2), (B, H, 8, D))
    bt = jnp.asarray(block_tables, jnp.int32)
    ln = jnp.asarray(ctx_lens, jnp.int32)
    if pps < n_pages:
        return _paged_decode_split(qr, k_pool, v_pool, bt, ln,
                                   float(scale), pps, interpret)
    s_pad = n_pages * bs

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, n_pages),
        in_specs=[
            pl.BlockSpec((1, 1, 8, D),
                         lambda b, h, j, bt, ln: (b, h, 0, 0)),
            pl.BlockSpec((1, bs, 1, D),
                         lambda b, h, j, bt, ln: (bt[b, j], 0, h, 0)),
            pl.BlockSpec((1, bs, 1, D),
                         lambda b, h, j, bt, ln: (bt[b, j], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 8, D),
                               lambda b, h, j, bt, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((8, s_pad), q.dtype),
            pltpu.VMEM((s_pad, D), q.dtype),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=float(scale),
                          block_size=bs, n_pages=n_pages),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, 8, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(bt, ln, qr, k_pool, v_pool)
    return out[:, :, 0][:, None]        # [B, H, 8, D] -> [B, 1, H, D]


def _paged_decode_split(qr, k_pool, v_pool, bt, ln, scale, pps,
                        interpret):
    """Split-K driver: pad the table out to whole splits, run the
    flash-decode body per (batch, head, split), merge the partials in
    one tiny jitted XLA reduction."""
    B, H, _, D = qr.shape
    _, bs, _, _ = k_pool.shape
    n_pages = bt.shape[1]
    n_splits = -(-n_pages // pps)
    pad_pages = n_splits * pps
    if pad_pages > n_pages:
        # padded pages point at block 0 (the garbage block); the
        # in-kernel (jg < n_pages) guard keeps them out of the scores
        bt = jnp.pad(bt, ((0, 0), (0, pad_pages - n_pages)))
    s_split = pps * bs

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, n_splits, pps),
        in_specs=[
            pl.BlockSpec((1, 1, 8, D),
                         lambda b, h, sp, j, bt, ln: (b, h, 0, 0)),
            pl.BlockSpec((1, bs, 1, D),
                         lambda b, h, sp, j, bt, ln:
                         (bt[b, sp * pps + j], 0, h, 0)),
            pl.BlockSpec((1, bs, 1, D),
                         lambda b, h, sp, j, bt, ln:
                         (bt[b, sp * pps + j], 0, h, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, 1, 8, D),
                         lambda b, h, sp, j, bt, ln: (b, h, sp, 0, 0)),
            pl.BlockSpec((1, 1, 1, 8, 128),
                         lambda b, h, sp, j, bt, ln: (b, h, sp, 0, 0)),
            pl.BlockSpec((1, 1, 1, 8, 128),
                         lambda b, h, sp, j, bt, ln: (b, h, sp, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((8, s_split), jnp.float32),
            pltpu.VMEM((s_split, D), qr.dtype),
        ],
    )
    o_parts, m, l = pl.pallas_call(
        functools.partial(_decode_kernel_split, scale=scale,
                          block_size=bs, pages_per_split=pps,
                          n_pages=n_pages),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, n_splits, 8, D), jnp.float32),
            jax.ShapeDtypeStruct((B, H, n_splits, 8, 128), jnp.float32),
            jax.ShapeDtypeStruct((B, H, n_splits, 8, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary")),
        interpret=interpret,
    )(bt, ln, qr, k_pool, v_pool)
    out = _merge_split_jit(str(jnp.dtype(qr.dtype)))(
        o_parts[:, :, :, 0, :], m[:, :, :, 0, 0], l[:, :, :, 0, 0])
    return out[:, None]                     # [B, H, D] -> [B, 1, H, D]


@functools.lru_cache(maxsize=None)
def _merge_split_jit(out_dtype: str):
    return jax.jit(functools.partial(_merge_splits,
                                     out_dtype=jnp.dtype(out_dtype)))


def gathered_dense_kv(pool, block_tables):
    """Dense ``[B, n_pages*block_size, H, D]`` view of every
    sequence's K or V through its block table (one vectorized
    gather)."""
    g = pool[jnp.asarray(block_tables, jnp.int32)]   # [B, P, bs, H, D]
    return g.reshape(g.shape[:1] + (-1,) + g.shape[3:])


# reference programs cached per (shape, dtype, scale): the bitwise
# contract is a COMPILED-program property — eager per-op dispatch lets
# XLA compile each op alone and round reductions differently (observed
# 1-ulp drift CPU-side), so the reference always runs jitted
_REF_CACHE: dict = {}


def paged_attention_reference(q, k_pool, v_pool, block_tables, ctx_lens,
                              scale=None):
    """Dense reference: gather K/V through the block table, then run
    the kernel's exact op sequence — per (sequence, head) single-row
    2-D dots, ``finfo.min`` pad mask, one ``jax.nn.softmax(f32)`` —
    compiled as ONE jitted program. Bitwise-equal (fp32) to the
    kernel (the loops mirror its grid steps one-for-one) and to a
    jitted ``nn.functional.flash_attention`` on H=1 slices of the
    contiguous K/V: at H=1 the dense path's batched einsum collapses
    to the same 2-D ``dot_general``, while an H-batched gemm is free
    to reassociate its reduction (observed 1-ulp drift on XLA CPU) —
    which is also why this reference loops heads instead of batching
    them. The flash equality is exact when the context is
    block-aligned (equal reduction widths); at ragged contexts the
    padded-width softmax/out reductions may regroup and drift 1 ulp
    vs the exact-width dense path — kernel-vs-reference stays bitwise
    regardless, since both run at the padded width."""
    B, _, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    key = (tuple(q.shape), str(jnp.asarray(q).dtype),
           tuple(k_pool.shape), int(np.asarray(block_tables).shape[1]),
           float(scale))
    fn = _REF_CACHE.get(key)
    if fn is None:
        fn = jax.jit(functools.partial(_reference_impl, scale=float(scale),
                                       B=B, H=H))
        if len(_REF_CACHE) > 256:
            _REF_CACHE.clear()
        _REF_CACHE[key] = fn
    return fn(jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
              jnp.asarray(block_tables, jnp.int32),
              jnp.asarray(ctx_lens, jnp.int32))


def paged_attention_split_reference(q, k_pool, v_pool, block_tables,
                                    ctx_lens, scale=None,
                                    pages_per_split=1):
    """Dense twin of the SPLIT-K body: gather K/V through the block
    table, then mirror the split kernel's op sequence one-for-one —
    per-page single-row score dots, per-split ``max/exp/sum`` and the
    unnormalized ``p @ V`` partial dot (f32 accumulation), then the
    exact :func:`_merge_splits` reduction — compiled as ONE jitted
    program. Bitwise-equal (fp32) to the split kernel by construction;
    vs the global-softmax :func:`paged_attention_reference` it is
    1-ulp class (the per-split rescaling reassociates the softmax
    reductions), which the tests assert as tight allclose."""
    B, _, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    key = ("split", tuple(q.shape), str(jnp.asarray(q).dtype),
           tuple(k_pool.shape), int(np.asarray(block_tables).shape[1]),
           float(scale), int(pages_per_split))
    fn = _REF_CACHE.get(key)
    if fn is None:
        # TWO compiled stages, mirroring the kernel path's program
        # structure (pallas partials, then the shared merge program):
        # fusing partials + merge into one XLA program lets the
        # compiler reassociate across the boundary (~1 ulp observed on
        # CPU), so the reference reuses the EXACT _merge_split_jit
        # program the kernel path runs
        fn = jax.jit(functools.partial(
            _split_partials_impl, scale=float(scale), B=B, H=H,
            pps=int(pages_per_split)))
        if len(_REF_CACHE) > 256:
            _REF_CACHE.clear()
        _REF_CACHE[key] = fn
    o_parts, m, l = fn(jnp.asarray(q), jnp.asarray(k_pool),
                       jnp.asarray(v_pool),
                       jnp.asarray(block_tables, jnp.int32),
                       jnp.asarray(ctx_lens, jnp.int32))
    out = _merge_split_jit(str(jnp.dtype(jnp.asarray(q).dtype)))(
        o_parts, m, l)
    return out[:, None]                              # (B, 1, H, D)


def _split_partials_impl(q, k_pool, v_pool, block_tables, ctx_lens, *,
                         scale, B, H, pps):
    """Dense mirror of the split kernel's per-(batch, head, split)
    partial computation: returns ``(o_parts [B,H,S,D] f32,
    m [B,H,S] f32, l [B,H,S] f32)``."""
    kd = gathered_dense_kv(k_pool, block_tables)     # [B, S_pad, H, D]
    vd = gathered_dense_kv(v_pool, block_tables)
    prec = _precision(q.dtype)
    bs = k_pool.shape[1]
    n_pages = block_tables.shape[1]
    n_splits = -(-n_pages // pps)
    D = q.shape[-1]
    all_o, all_m, all_l = [], [], []
    for b in range(B):
        heads_o, heads_m, heads_l = [], [], []
        for h in range(H):
            parts_o, parts_m, parts_l = [], [], []
            for sp in range(n_splits):
                cols = []
                vals = []
                for j in range(pps):
                    jg = sp * pps + j
                    if jg >= n_pages:
                        # padded page: NEG_INF scores, zero V — the
                        # kernel's untouched-scratch state
                        cols.append(jnp.full((1, bs), NEG_INF,
                                             jnp.float32))
                        vals.append(jnp.zeros((bs, D), q.dtype))
                        continue
                    lo = jg * bs
                    s = jax.lax.dot_general(
                        q[b, :, h], kd[b, lo:lo + bs, h],
                        (((1,), (1,)), ((), ())),
                        precision=prec) * scale       # (1, bs)
                    valid = (jnp.arange(bs) + lo) < ctx_lens[b]
                    s = jnp.where(valid[None, :],
                                  s.astype(jnp.float32), NEG_INF)
                    # the kernel skips pages wholly past the context:
                    # its scratch keeps NEG_INF/0 there
                    dead = jnp.asarray(lo, jnp.int32) >= ctx_lens[b]
                    cols.append(jnp.where(dead, NEG_INF, s))
                    vals.append(jnp.where(
                        dead, jnp.zeros_like(vd[b, lo:lo + bs, h]),
                        vd[b, lo:lo + bs, h]))
                s = jnp.concatenate(cols, axis=1)     # (1, S_split) f32
                v = jnp.concatenate(vals, axis=0)     # (S_split, D)
                m = jnp.max(s, axis=1, keepdims=True)
                safe_m = jnp.where(m == NEG_INF, 0.0, m)
                p = jnp.exp(s - safe_m)
                p = jnp.where(s == NEG_INF, 0.0, p)
                l = jnp.sum(p, axis=1, keepdims=True)
                o = jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                parts_o.append(o[0])                  # (D,) f32
                parts_m.append(m[0, 0])
                parts_l.append(l[0, 0])
            heads_o.append(jnp.stack(parts_o))        # (S, D)
            heads_m.append(jnp.stack(parts_m))        # (S,)
            heads_l.append(jnp.stack(parts_l))
        all_o.append(jnp.stack(heads_o))              # (H, S, D)
        all_m.append(jnp.stack(heads_m))
        all_l.append(jnp.stack(heads_l))
    return (jnp.stack(all_o), jnp.stack(all_m), jnp.stack(all_l))


def _reference_impl(q, k_pool, v_pool, block_tables, ctx_lens, *,
                    scale, B, H):
    kd = gathered_dense_kv(k_pool, block_tables)     # [B, S_pad, H, D]
    vd = gathered_dense_kv(v_pool, block_tables)
    prec = _precision(q.dtype)
    s_pad = kd.shape[1]
    out = []
    for b in range(B):
        valid = jnp.arange(s_pad) < ctx_lens[b]
        heads = []
        for h in range(H):
            s = jax.lax.dot_general(
                q[b, :, h], kd[b, :, h], (((1,), (1,)), ((), ())),
                precision=prec) * scale              # (1, S_pad)
            s = jnp.where(valid[None, :], s, jnp.finfo(s.dtype).min)
            p = jax.nn.softmax(s.astype(jnp.float32),
                               axis=-1).astype(q.dtype)
            heads.append(jax.lax.dot_general(
                p, vd[b, :, h], (((1,), (0,)), ((), ())),
                precision=prec))                     # (1, D)
        out.append(jnp.stack(heads, axis=1))         # (1, H, D)
    return jnp.stack(out)                            # (B, 1, H, D)
