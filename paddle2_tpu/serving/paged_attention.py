"""Pallas paged-attention decode kernel + dense reference path.

The decode-side half of PagedAttention (Kwon et al. SOSP'23) on the
flash kernel's machinery (``kernels/pallas_flash.py``): at decode each
sequence contributes ONE query token and attends over its whole cached
prefix, whose K/V live scattered across fixed-size blocks of the
shared pool (``serving/block_cache.py``). The kernel walks the
sequence's block table — scalar-prefetched so the index maps can
compute DMA source blocks before the body runs (the
``PrefetchScalarGridSpec`` pattern from the official TPU paged
kernels) — and gathers K/V blocks into VMEM.

Numerics contract (the serving acceptance gate): the kernel's output
is **bitwise identical in fp32** to :func:`paged_attention_reference`
(dense gather through the same table) which in turn is bitwise
identical to ``nn.functional.flash_attention`` on the contiguously
gathered K/V. That chain holds because all three run the *same op
sequence*: ``dot(q, k) * scale`` -> mask with ``finfo.min`` ->
``jax.nn.softmax(f32)`` -> ``dot(p, v)``, i.e. the exact arithmetic of
``kernels/attention._sdpa_xla`` (the dense decode path — decode shapes
never hit the tiled flash kernel, whose online softmax would reorder
the reductions). The per-page score dots write into one
``[8, n_pages*block_size]`` score buffer and the softmax runs ONCE
over the full row, so block fragmentation cannot change a single bit:
the gathered values, not their physical placement, define the result.
Pad slots hold ``finfo.min`` scores, which underflow to exactly 0.0
probability, and context lengths are kept multiples of 8 (the repo's
row-tiling minimum) so padded-width reductions group lanes identically
to exact-width ones.

VMEM: scores 8 x S_max + V S_max x D per (batch, head) program — at
the serving ceiling (S 2048, D 128, f32) ~1.1 MB, comfortably scoped.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..kernels.pallas_flash import _interpret_default

# jax renamed TPUCompilerParams -> CompilerParams; support both so the
# kernel loads on every jax this repo meets
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

__all__ = ["paged_attention_decode", "paged_attention_reference",
           "gathered_dense_kv"]


def _precision(dtype):
    # mirror ops.linalg._mxu_precision: bf16/f16 pinned to DEFAULT so
    # the MXU keeps its native-rate path; f32 inherits the global
    # setting — the same choice _sdpa_xla makes, which the bitwise
    # contract depends on
    if jnp.dtype(dtype) in (jnp.bfloat16, jnp.float16):
        return jax.lax.Precision.DEFAULT
    return None


def _decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   s_buf, v_buf, *, scale, block_size, n_pages):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        # dead/pad slots: finfo.min scores (exactly-0 probability after
        # the f32 softmax) and zero V
        s_buf[:] = jnp.full_like(s_buf, jnp.finfo(s_buf.dtype).min)
        v_buf[:] = jnp.zeros_like(v_buf)

    ctx = len_ref[b]

    @pl.when(j * block_size < ctx)
    def _gather():
        # the score dot runs on a SINGLE query row: the gemm's row
        # count changes XLA's reduction grouping (an 8-row dot drifts
        # ~1 ulp from the 1-row dot flash_attention's decode einsum
        # collapses to), and the bitwise contract hinges on matching
        # it exactly. The tile itself stays 8 rows for TPU sublane
        # layout; rows 1..7 are dead weight.
        q = q_ref[0, 0][:1]                   # (1, D) native dtype
        k = k_ref[0, :, 0, :]                 # (bs, D)
        v = v_ref[0, :, 0, :]                 # (bs, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            precision=_precision(q.dtype)) * scale
        cols = jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1) + j * block_size
        s = jnp.where(cols < ctx, s, jnp.finfo(s.dtype).min)
        s_buf[:1, pl.ds(j * block_size, block_size)] = \
            s.astype(s_buf.dtype)
        v_buf[pl.ds(j * block_size, block_size), :] = v

    @pl.when(j == n_pages - 1)
    def _finalize():
        # ONE global softmax over the assembled row — the same
        # softmax(f32)-then-matmul sequence as _sdpa_xla, NOT the
        # online-softmax recurrence (whose per-block rescaling would
        # round differently and break the bitwise contract)
        probs = jax.nn.softmax(
            s_buf[:1].astype(jnp.float32), axis=-1).astype(o_ref.dtype)
        o = jax.lax.dot_general(
            probs, v_buf[:], (((1,), (0,)), ((), ())),
            precision=_precision(o_ref.dtype))       # (1, D)
        o_ref[0, 0] = jnp.broadcast_to(o, o_ref.shape[2:]) \
            .astype(o_ref.dtype)


def paged_attention_decode(q, k_pool, v_pool, block_tables, ctx_lens,
                           scale=None, interpret=None):
    """Paged decode attention.

    q: ``[B, 1, H, D]`` (paddle layout) — one new token per sequence.
    k_pool/v_pool: ``[num_blocks, block_size, H, D]`` shared pools.
    block_tables: int32 ``[B, n_pages]`` physical block ids per
    sequence (pad rows with the garbage block).
    ctx_lens: int32 ``[B]`` valid keys per sequence (including the
    token just appended). Returns ``[B, 1, H, D]``.
    """
    B, _, H, D = q.shape
    n_blocks, bs, _, _ = k_pool.shape
    n_pages = block_tables.shape[1]
    s_pad = n_pages * bs
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    if interpret is None:
        interpret = _interpret_default()
    # q rides as [B, H, 8, D]: 8 identical rows satisfy the TPU
    # sublane-tiling minimum; row 0 is the answer
    qr = jnp.broadcast_to(jnp.swapaxes(q, 1, 2), (B, H, 8, D))
    bt = jnp.asarray(block_tables, jnp.int32)
    ln = jnp.asarray(ctx_lens, jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, n_pages),
        in_specs=[
            pl.BlockSpec((1, 1, 8, D),
                         lambda b, h, j, bt, ln: (b, h, 0, 0)),
            pl.BlockSpec((1, bs, 1, D),
                         lambda b, h, j, bt, ln: (bt[b, j], 0, h, 0)),
            pl.BlockSpec((1, bs, 1, D),
                         lambda b, h, j, bt, ln: (bt[b, j], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 8, D),
                               lambda b, h, j, bt, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((8, s_pad), q.dtype),
            pltpu.VMEM((s_pad, D), q.dtype),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=float(scale),
                          block_size=bs, n_pages=n_pages),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, 8, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(bt, ln, qr, k_pool, v_pool)
    return out[:, :, 0][:, None]        # [B, H, 8, D] -> [B, 1, H, D]


def gathered_dense_kv(pool, block_tables):
    """Dense ``[B, n_pages*block_size, H, D]`` view of every
    sequence's K or V through its block table (one vectorized
    gather)."""
    g = pool[jnp.asarray(block_tables, jnp.int32)]   # [B, P, bs, H, D]
    return g.reshape(g.shape[:1] + (-1,) + g.shape[3:])


# reference programs cached per (shape, dtype, scale): the bitwise
# contract is a COMPILED-program property — eager per-op dispatch lets
# XLA compile each op alone and round reductions differently (observed
# 1-ulp drift CPU-side), so the reference always runs jitted
_REF_CACHE: dict = {}


def paged_attention_reference(q, k_pool, v_pool, block_tables, ctx_lens,
                              scale=None):
    """Dense reference: gather K/V through the block table, then run
    the kernel's exact op sequence — per (sequence, head) single-row
    2-D dots, ``finfo.min`` pad mask, one ``jax.nn.softmax(f32)`` —
    compiled as ONE jitted program. Bitwise-equal (fp32) to the
    kernel (the loops mirror its grid steps one-for-one) and to a
    jitted ``nn.functional.flash_attention`` on H=1 slices of the
    contiguous K/V: at H=1 the dense path's batched einsum collapses
    to the same 2-D ``dot_general``, while an H-batched gemm is free
    to reassociate its reduction (observed 1-ulp drift on XLA CPU) —
    which is also why this reference loops heads instead of batching
    them. The flash equality is exact when the context is
    block-aligned (equal reduction widths); at ragged contexts the
    padded-width softmax/out reductions may regroup and drift 1 ulp
    vs the exact-width dense path — kernel-vs-reference stays bitwise
    regardless, since both run at the padded width."""
    B, _, H, D = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    key = (tuple(q.shape), str(jnp.asarray(q).dtype),
           tuple(k_pool.shape), int(np.asarray(block_tables).shape[1]),
           float(scale))
    fn = _REF_CACHE.get(key)
    if fn is None:
        fn = jax.jit(functools.partial(_reference_impl, scale=float(scale),
                                       B=B, H=H))
        if len(_REF_CACHE) > 256:
            _REF_CACHE.clear()
        _REF_CACHE[key] = fn
    return fn(jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
              jnp.asarray(block_tables, jnp.int32),
              jnp.asarray(ctx_lens, jnp.int32))


def _reference_impl(q, k_pool, v_pool, block_tables, ctx_lens, *,
                    scale, B, H):
    kd = gathered_dense_kv(k_pool, block_tables)     # [B, S_pad, H, D]
    vd = gathered_dense_kv(v_pool, block_tables)
    prec = _precision(q.dtype)
    s_pad = kd.shape[1]
    out = []
    for b in range(B):
        valid = jnp.arange(s_pad) < ctx_lens[b]
        heads = []
        for h in range(H):
            s = jax.lax.dot_general(
                q[b, :, h], kd[b, :, h], (((1,), (1,)), ((), ())),
                precision=prec) * scale              # (1, S_pad)
            s = jnp.where(valid[None, :], s, jnp.finfo(s.dtype).min)
            p = jax.nn.softmax(s.astype(jnp.float32),
                               axis=-1).astype(q.dtype)
            heads.append(jax.lax.dot_general(
                p, vd[b, :, h], (((1,), (0,)), ((), ())),
                precision=prec))                     # (1, D)
        out.append(jnp.stack(heads, axis=1))         # (1, H, D)
    return jnp.stack(out)                            # (B, 1, H, D)
