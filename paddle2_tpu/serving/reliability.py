"""Serving reliability plane: typed failure semantics, admission
control, and zero-drop weight hot-swap.

PRs 1-6 gave *training* an industrial fault-tolerance discipline
(retry, chaos drills, elastic recovery, SDC defense); this module
gives the PR 9 serving stack the same plane. Three concerns live
here, all host-side and deterministic (time enters only through the
caller-supplied virtual-clock stamps — no wall clocks):

* **Typed errors** — every way a request can fail is a distinct
  exception type, so callers (and the troubleshooting matrix in the
  README) can tell *shed* from *evicted* from *deadline-exceeded*
  from *engine-failed* without string-matching. ``RequestRejected``
  subclasses are raised AT SUBMIT TIME; ``DeadlineExceeded`` /
  ``EngineFailedError`` also land on ``Sequence.error`` when the
  failure happens after submission (shed from the queue, engine
  death) — :meth:`Sequence.check` re-raises them.

* **Admission control** (:class:`ReliabilityConfig`) — a bounded
  admission queue with per-request deadlines and priorities. The
  overload policy sheds the LOWEST-priority waiting request first
  (ties: youngest) and NEVER touches in-flight sequences — an
  admitted request is either served or evicted-and-requeued (PR 9
  semantics), not dropped. Deadlines are enforced at admission
  boundaries against the virtual clock: an expired waiting request
  is shed with :class:`DeadlineExceeded` instead of wasting prefill
  compute on an answer nobody is waiting for.

* **Weight hot-swap** (:class:`HotSwapController`) — staged rollout
  of new checkpoint weights across a fleet of running engines, with
  rollback. ``TracedProgram``-style weights-as-args (the PR 9 runner
  design) makes a swap an ARGUMENT change between decode steps, not
  a recompile: the controller's contract is zero dropped requests
  and zero extra compiled programs, gated by
  ``bench.py --serving-reliability``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence as Seq

__all__ = [
    "ServingError", "RequestRejected", "QueueFullError",
    "PromptTooLongError", "DeadlineExceeded", "EngineFailedError",
    "WeightSwapError", "ReliabilityConfig", "SLOConfig",
    "HotSwapController", "flight_record",
]


def flight_record(**fields) -> None:
    """One shared emitter for every serving flight-recorder span
    (``kind="serving"``) — scheduler, engine, router, and hot-swap all
    route through here so the span format has a single owner. Spans
    that carry a clock stamp (``t=``) and a trace id (``tid=`` /
    ``tids=``) are mirrored into the request-tracing plane
    (:mod:`~paddle2_tpu.observability.tracing`), so the flight ring,
    the per-request traces, and the chrome view all share one set of
    instrumentation sites and event names. Each plane inherits its own
    one-attribute-load no-op when disabled."""
    from ..distributed.fault_tolerance import flight_recorder
    from ..observability import tracing
    # None-valued fields (an unstamped clock, an untraced request) are
    # dropped rather than serialized as nulls in every dump
    fields = {k: v for k, v in fields.items() if v is not None}
    flight_recorder.record("serving", **fields)
    tracing.serving_span(fields)


# ---------------------------------------------------------------- errors
class ServingError(RuntimeError):
    """Base of every typed serving failure."""


class RequestRejected(ServingError, ValueError):
    """The request was refused at (or after) submission — admission
    control, not a server fault. Subclasses say why. Also a
    ``ValueError`` so callers of the pre-typed submit API keep
    working."""


class QueueFullError(RequestRejected):
    """Bounded admission queue is full and the overload policy found
    no lower-priority waiting request to shed."""


class PromptTooLongError(RequestRejected):
    """``len(prompt) + max_new_tokens`` exceeds ``max_model_len`` —
    rejected at submit time, before any blocks or compute are spent
    (letting it through surfaces later as an illegible block-coverage
    stall)."""


class DeadlineExceeded(ServingError):
    """The request's deadline passed before it could be admitted (or
    the caller observed it expired). Shed requests carry this on
    ``Sequence.error``."""


class EngineFailedError(ServingError):
    """The engine died (chaos ``kill_engine``, a poisoned device, an
    operator kill). In-flight sequences are recoverable from their
    host-side token logs via ``ServingEngine.recover_inflight`` — the
    failover router re-prefills them on a healthy engine."""


class WeightSwapError(ServingError):
    """A hot-swap payload does not match the running model (length /
    shape / dtype) — the swap is refused atomically, nothing is
    half-applied."""


# --------------------------------------------------------------- SLOs
@dataclass
class SLOConfig:
    """Service-level objectives for one engine (ISSUE 13).

    Targets are per-request, evaluated on the engine's clock at finish
    time (virtual in the simulators — the SLO counters are then
    bit-stable): TTFT (arrival -> first token), TPOT (mean seconds per
    generated token after the first), and e2e latency. ``None`` skips
    a dimension. A request is GOOD when every configured dimension
    meets its target; shed / deadline-expired / failed requests are
    BAD by definition (they consumed error budget without an answer).

    The burn rate follows the SRE error-budget convention:
    ``bad_fraction / (1 - availability_target)`` — 1.0 means the
    budget burns exactly at the sustainable rate, above 1.0 the budget
    exhausts early. Exported through the metrics plane as
    ``serving_slo_{good,bad}_total`` counters (plus per-dimension
    ``serving_slo_checks_total{slo=...,verdict=...}``) and the
    ``serving_slo_burn_rate`` gauge."""
    ttft_target_s: Optional[float] = None
    tpot_target_s: Optional[float] = None
    e2e_target_s: Optional[float] = None
    availability_target: float = 0.99

    @property
    def error_budget(self) -> float:
        return max(1.0 - float(self.availability_target), 1e-9)


# ---------------------------------------------------- admission control
@dataclass
class ReliabilityConfig:
    """Admission-control & load-shedding knobs for one engine.

    ``max_queue_depth=None`` keeps the PR 9 unbounded-queue behavior;
    everything else only matters once a bound is set. Priorities are
    ints, HIGHER = more important. ``default_deadline_s`` is relative
    to each request's ``arrival_t`` (virtual clock). ``slo`` opts the
    engine into per-request :class:`SLOConfig` accounting."""
    max_queue_depth: Optional[int] = None
    default_priority: int = 0
    default_deadline_s: Optional[float] = None
    # overload policy: shed the lowest-priority waiting request to
    # make room for a strictly-higher-priority arrival (False =
    # always reject the arrival when full)
    shed_on_full: bool = True
    slo: Optional[SLOConfig] = None

    def deadline_for(self, arrival_t: float,
                     deadline_s: Optional[float]) -> Optional[float]:
        d = self.default_deadline_s if deadline_s is None else deadline_s
        return None if d is None else float(arrival_t) + float(d)


# ---------------------------------------------------------- hot swap
class HotSwapController:
    """Staged zero-drop rollout of new weights across running engines.

    Lifecycle::

        ctl = HotSwapController(engines, new_weights)
        while ctl.stage_next(now) is not None:   # one engine per stage
            ...serve traffic, watch ctl.healthy(verify)...
        # ctl.state == "committed", or on a bad canary:
        ctl.rollback(now)                        # restore old weights

    Each stage swaps ONE engine between its decode steps (weights ride
    as program arguments — same shapes/dtypes, so the compiled decode
    census cannot grow). The previous weights are captured per engine
    at stage time, so ``rollback`` is itself just another swap, applied
    in reverse stage order. An engine that died before its stage is
    skipped (the failover router owns its sequences); an engine that
    dies MID-stage leaves the controller free to roll the healthy
    stages back.

    ``verify`` (optional) runs after every stage; returning ``False``
    triggers an automatic rollback and marks the controller
    ``rolled_back`` — the staged-canary pattern.

    ``source`` (optional) names the checkpoint lineage the new weights
    came from — ``CheckpointManager.swap_source()`` returns the right
    shape (``{"session", "generation", "step"}``). When present, every
    hot-swap span (stage/commit/canary-failed/rollback, and the
    engine-side ``hot_swap`` span that mirrors into per-request
    traces) carries the train-side restart generation, so serve traces
    join to the producing training lineage by construction."""

    def __init__(self, engines: Seq, new_weights,
                 verify: Optional[Callable] = None,
                 source: Optional[dict] = None):
        self.engines = list(engines)
        self.new_weights = new_weights
        self.verify = verify
        self.source = dict(source) if source else None
        self._prev = {}              # engine idx -> pre-swap arrays
        self.staged: List[int] = []
        self.state = "pending"       # rolling|committed|rolled_back

    def _record(self, event: str, **fields) -> None:
        # flatten the checkpoint lineage into the span so the fields
        # are greppable in dumps (nested dicts survive JSON but defeat
        # `serve_doctor`-style field scans)
        if self.source is not None:
            fields.setdefault("generation", self.source.get("generation"))
            fields.setdefault("ckpt_step", self.source.get("step"))
            fields.setdefault("session", self.source.get("session"))
        flight_record(event=event, **fields)

    def _done_staging(self) -> bool:
        return all(i in self._prev or getattr(e, "failed", False)
                   for i, e in enumerate(self.engines))

    def _commit(self, now: float) -> None:
        """The ONE owner of the commit transition (idempotent)."""
        if self.state != "committed":
            self.state = "committed"
            self._record("hot_swap_commit", t=now,
                         stages=len(self.staged))

    def stage_next(self, now: float = 0.0) -> Optional[int]:
        """Swap the next alive, unstaged engine. Returns its index, or
        None when every engine is staged (state -> "committed")."""
        if self.state in ("committed", "rolled_back"):
            return None
        self.state = "rolling"
        for idx, eng in enumerate(self.engines):
            if idx in self._prev or getattr(eng, "failed", False):
                continue
            self._prev[idx] = eng.swap_weights(self.new_weights, now=now,
                                               source=self.source)
            self.staged.append(idx)
            self._record("hot_swap_stage", engine=idx, t=now,
                         stage=len(self.staged))
            if self.verify is not None and not self.verify(eng):
                self._record("hot_swap_canary_failed", engine=idx, t=now)
                self.rollback(now)
                return idx
            if self._done_staging():
                self._commit(now)
            return idx
        if self.staged:
            # nothing left to stage AND at least one engine got the
            # new weights; a fleet that was entirely dead/unstageable
            # must NOT report "committed" for a rollout that touched
            # nothing
            self._commit(now)
        return None

    def rollback(self, now: float = 0.0) -> List[int]:
        """Restore the pre-swap weights on every staged engine, newest
        stage first. Engines that died since their stage are skipped.
        Returns the indices rolled back. A rollback before any stage
        is a no-op (nothing was touched, the state is unchanged)."""
        if not self.staged:
            return []
        rolled = []
        for idx in reversed(self.staged):
            eng = self.engines[idx]
            if getattr(eng, "failed", False):
                continue
            eng.swap_weights(self._prev[idx], now=now)
            rolled.append(idx)
        self.state = "rolled_back"
        self._record("hot_swap_rollback", t=now, engines=rolled)
        return rolled
