"""Continuous-batching scheduler: admit/evict per decode step.

The Orca iteration-level scheduling model (Yu et al. OSDI'22): the
decode batch is re-formed at EVERY step — finished sequences leave
immediately, waiting requests join as soon as a batch slot and KV
blocks are free — instead of the static-batch regime where the whole
batch waits for its slowest member.

Three policies live here, all host-side and deterministic:

* **Admission** (FIFO + prefill budget): waiting requests are admitted
  oldest-first when (a) a decode slot is free, (b) the allocator can
  cover their prompt blocks, and (c) the per-round prefill token
  budget is not exhausted. The budget is the prefill/decode
  disaggregation knob: prefill compute runs on its own lane (a
  separate instance in a disaggregated deployment; between decode
  steps on one chip), and capping admitted prefill tokens per round
  bounds how long the decode batch can go without a step even on the
  single-chip fallback.
* **Preemption by eviction** (LIFO victim): when a running sequence
  needs a block and the free list is empty, the NEWEST running
  sequence is evicted — all its blocks freed, state back to WAITING at
  the FRONT of the queue (it re-prefills prompt+generated-so-far on
  re-admission, the vLLM recompute strategy). LIFO keeps the oldest
  requests making progress, so no request starves.
* **Bucketed shapes**: the decode batch is padded to a fixed set of
  (batch, pages) buckets so the compiled decode program is reused
  across compositions — the serving bench gates that the number of
  compiled decode programs never exceeds ``len(batch_buckets) x
  len(page_buckets)``.
* **Admission control & load shedding** (PR 11, opt-in via
  ``SchedulerConfig.reliability``): a bounded admission queue with
  per-request priorities and deadlines. When the queue is full, the
  overload policy sheds the LOWEST-priority waiting request (ties:
  youngest) to admit a strictly-higher-priority arrival — in-flight
  sequences are always honored (eviction requeues, shedding only ever
  removes WAITING work). Expired deadlines are shed at every
  admission boundary against the caller's virtual clock.

Scheduler decisions (admit / evict / requeue / shed) land in the
flight-recorder ring (one-attribute-load no-op when off) so
``flight_doctor`` can post-mortem a serving crash.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .block_cache import (BlockAllocator, BlockTable, OutOfBlocksError,
                          blocks_for_tokens)
from .reliability import (DeadlineExceeded, QueueFullError,
                          ReliabilityConfig, ServingError,
                          flight_record as _flight_record)

__all__ = ["Request", "Sequence", "SeqState", "SchedulerConfig",
           "ContinuousBatchingScheduler"]


@dataclass
class Request:
    """One generation request as submitted by a client.

    ``priority`` (higher = more important) and ``deadline_t``
    (ABSOLUTE virtual-clock stamp, None = none) drive the admission
    controller; both default to the PR 9 don't-care values.
    ``trace_id`` is the STABLE identity the request-tracing plane keys
    spans by: ``req_id`` is re-keyed when a failover adoption moves
    the sequence to another engine, ``trace_id`` never changes (the
    engine defaults it to the original ``req_id``; the router stamps
    its fleet-global id)."""
    req_id: int
    prompt: List[int]
    max_new_tokens: int
    arrival_t: float = 0.0
    priority: int = 0
    deadline_t: Optional[float] = None
    trace_id: Optional[int] = None


class SeqState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"
    SHED = "shed"


class Sequence:
    """Scheduler-side state of one request."""

    def __init__(self, request: Request, allocator: BlockAllocator):
        self.request = request
        self.tokens: List[int] = list(request.prompt)
        self.table = BlockTable(allocator)
        self.state = SeqState.WAITING
        self.first_token_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self.evictions = 0
        self.recoveries = 0          # corruption / engine-failure rebuilds
        self.error: Optional[ServingError] = None   # set when SHED
        # leading tokens whose KV came from the prefix cache at the
        # LAST admission (the engine scatters only past this point)
        self.prefix_cached_tokens = 0
        # KV-tier attribution for the LAST admission (ISSUE 16): how
        # many of the cached blocks were promoted from the host tier /
        # a DCN peer, and the peer transfer's modeled seconds — the
        # engine prices the spill_fetch stall from these
        self.kv_fetched_host = 0
        self.kv_fetched_peer = 0
        self.kv_peer_fetch_s = 0.0
        # earliest stamp migrated KV is on-device (a failover
        # migration's DCN transfer completes here; admission waits)
        self.kv_ready_t = 0.0

    def check(self) -> "Sequence":
        """Raise the typed error a post-submission failure recorded
        (shed / deadline / engine death); returns self when healthy."""
        if self.error is not None:
            raise self.error
        return self

    def rebind(self, allocator: BlockAllocator) -> None:
        """Point the sequence at a FRESH empty table on ``allocator``
        WITHOUT releasing the old blocks — used when the old table is
        untrustworthy (corruption) or gone (its engine died). The
        token log is host state and survives; re-admission re-prefills
        it, which the eviction-exactness guarantee proves is
        token-for-token identical to never having lost the KV."""
        self.table = BlockTable(allocator)
        self.prefix_cached_tokens = 0     # re-resolved at re-admission

    @property
    def req_id(self) -> int:
        return self.request.req_id

    @property
    def trace_id(self) -> Optional[int]:
        return self.request.trace_id

    @property
    def priority(self) -> int:
        return self.request.priority

    @property
    def deadline_t(self) -> Optional[float]:
        return self.request.deadline_t

    @property
    def num_cached(self) -> int:
        return self.table.num_tokens

    @property
    def generated(self) -> List[int]:
        return self.tokens[len(self.request.prompt):]

    @property
    def done(self) -> bool:
        return len(self.generated) >= self.request.max_new_tokens

    def __repr__(self):
        return (f"Sequence(req={self.req_id}, state={self.state.value}, "
                f"tokens={len(self.tokens)}, cached={self.num_cached})")


@dataclass
class SchedulerConfig:
    max_batch: int = 8
    # power-of-two-ish ladders; padded shapes key the compiled decode
    # programs, so these two lists BOUND the program count
    batch_buckets: Tuple[int, ...] = (1, 2, 4, 8)
    page_buckets: Tuple[int, ...] = (2, 4, 8, 16)
    # prefill/decode disaggregation: max prompt tokens admitted per
    # scheduling round (0 = unlimited)
    prefill_budget_tokens: int = 512
    # admission control / load shedding (None = PR 9 behavior:
    # unbounded queue, no deadlines)
    reliability: Optional[ReliabilityConfig] = None

    def __post_init__(self):
        self.batch_buckets = tuple(sorted(set(self.batch_buckets)))
        self.page_buckets = tuple(sorted(set(self.page_buckets)))
        if self.batch_buckets[-1] < self.max_batch:
            raise ValueError("largest batch bucket must cover max_batch")

    @property
    def program_budget(self) -> int:
        return len(self.batch_buckets) * len(self.page_buckets)

    def batch_bucket(self, n: int) -> int:
        for b in self.batch_buckets:
            if n <= b:
                return b
        raise ValueError(f"batch {n} exceeds largest bucket "
                         f"{self.batch_buckets[-1]}")

    def page_bucket(self, n: int) -> int:
        for p in self.page_buckets:
            if n <= p:
                return p
        raise ValueError(f"{n} pages exceed largest bucket "
                         f"{self.page_buckets[-1]}")


class ContinuousBatchingScheduler:
    """Pure-host scheduling core; the engine owns the actual compute.

    The engine drives it as::

        admitted = sched.admit()            # -> seqs to prefill
        ...prefill each, mark running...
        batch = sched.running()             # current decode batch
        victims = sched.reserve_decode_slots()   # may evict
        ...run one decode step over sched.running()...
    """

    def __init__(self, config: SchedulerConfig, allocator: BlockAllocator):
        self.config = config
        self.allocator = allocator
        self.reliability = config.reliability or ReliabilityConfig()
        # CoW prefix cache (engine-installed; None = PR 9 behavior):
        # admission consults it so a hit's shared blocks don't count
        # against the free list
        self.prefix_cache = None
        self.engine_id = 0          # mirrored by the owning engine
        self.waiting: List[Sequence] = []
        self._running: List[Sequence] = []      # admission order
        self.finished: List[Sequence] = []
        self.shed: List[Sequence] = []
        self.total_evictions = 0
        self.total_shed = 0
        # SLO ledger (reliability.slo opt-in): good/bad request counts
        # driving the burn-rate gauge
        self.slo_good = 0
        self.slo_bad = 0

    # -- introspection ---------------------------------------------------
    def running(self) -> List[Sequence]:
        return list(self._running)

    @property
    def queue_depth(self) -> int:
        return len(self.waiting)

    @staticmethod
    def _in_flight(seq: Sequence) -> bool:
        """True once a sequence has ever been admitted: an evicted or
        recovered sequence waiting to resume is IN-FLIGHT work (tokens
        already accepted), not a fresh arrival — it is never a shed
        candidate and its deadline no longer applies (deadlines gate
        ADMISSION; admitted work runs to completion)."""
        return seq.evictions > 0 or seq.recoveries > 0

    # -- submission ------------------------------------------------------
    def submit(self, seq: Sequence) -> None:
        """Enqueue a new request. With a bounded admission queue
        (``reliability.max_queue_depth``), a full queue either sheds
        the lowest-priority waiting request (only if STRICTLY lower
        priority than the arrival — ties reject the arrival, FIFO
        fairness) or raises :class:`QueueFullError`. In-flight
        sequences are never candidates: eviction requeues bypass this
        bound via :meth:`requeue_front`, and an evicted/recovered
        sequence back in the queue is exempt from victim selection."""
        depth = self.reliability.max_queue_depth
        if depth is not None and len(self.waiting) >= depth:
            victim = None
            shippable = [s for s in self.waiting
                         if not self._in_flight(s)]
            if self.reliability.shed_on_full and shippable:
                # lowest priority first; ties broken by YOUNGEST
                # (latest queue position) so older work keeps its place
                victim = min(reversed(shippable),
                             key=lambda s: s.priority)
            if victim is None or victim.priority >= seq.priority:
                raise QueueFullError(
                    f"admission queue full ({len(self.waiting)} >= "
                    f"{depth}) and no waiting request has priority < "
                    f"{seq.priority}")
            self._shed(victim, QueueFullError(
                f"shed (priority {victim.priority}) for arrival "
                f"req {seq.req_id} (priority {seq.priority})"),
                now=seq.request.arrival_t)
        self.waiting.append(seq)

    def requeue_front(self, seq: Sequence, now: Optional[float] = None,
                      cause: str = "evict") -> None:
        """Put a previously-admitted sequence back at the FRONT of the
        queue (eviction, corruption recovery, engine-failover
        adoption): preempted work resumes before new arrivals and is
        exempt from the admission bound — in-flight is honored."""
        seq.state = SeqState.WAITING
        self.waiting.insert(0, seq)
        _flight_record(event="requeue", req=seq.req_id,
                       tid=seq.trace_id, t=now, cause=cause,
                       engine=self.engine_id, tokens=len(seq.tokens))

    # -- load shedding ---------------------------------------------------
    def _shed(self, seq: Sequence, err: ServingError,
              now: Optional[float] = None) -> None:
        self.waiting.remove(seq)
        self.mark_shed(seq, err, now=now)

    def mark_shed(self, seq: Sequence, err: ServingError,
                  now: Optional[float] = None) -> None:
        """Shed bookkeeping for a sequence NOT in the waiting queue —
        e.g. a recovered fresh arrival the adopting engine's bounded
        queue refuses at failover time."""
        from ..observability import metrics
        seq.state = SeqState.SHED
        seq.error = err
        self.shed.append(seq)
        self.total_shed += 1
        reason = ("deadline" if isinstance(err, DeadlineExceeded)
                  else "overload")
        metrics.inc("serving_shed_total", reason=reason)
        if reason == "deadline":
            metrics.inc("serving_deadline_exceeded_total")
        if self.reliability.slo is not None:
            # a shed request consumed error budget without an answer
            self._note_slo_verdict(False)
        _flight_record(event="shed", req=seq.req_id, tid=seq.trace_id,
                       t=now, reason=reason, engine=self.engine_id,
                       priority=seq.priority)

    def expire_deadlines(self, now: float) -> List[Sequence]:
        """Shed every never-admitted WAITING sequence whose deadline
        has passed — called at each admission boundary. In-flight work
        is honored to completion: RUNNING sequences are untouched, and
        an evicted/recovered sequence back in the queue already has
        accepted tokens, so its (admission) deadline no longer
        applies."""
        expired = [s for s in self.waiting
                   if s.deadline_t is not None and s.deadline_t < now
                   and not self._in_flight(s)]
        for s in expired:
            self._shed(s, DeadlineExceeded(
                f"req {s.req_id} deadline {s.deadline_t:.6f} < now "
                f"{now:.6f} before admission"), now=now)
        return expired

    # -- admission -------------------------------------------------------
    def admit(self, now: float = 0.0) -> List[Sequence]:
        """Pick waiting sequences to prefill this round: FIFO, bounded
        by free decode slots, allocator coverage for the WHOLE current
        token list (prompt + any pre-eviction generation), and the
        prefill token budget. Admitted sequences get their blocks
        allocated here; the engine must prefill and mark them RUNNING.
        A request whose blocks cannot be covered blocks the queue
        (FIFO — skipping it would starve long prompts forever).
        Expired deadlines are shed first, against ``now``."""
        self.expire_deadlines(now)
        admitted: List[Sequence] = []
        budget = self.config.prefill_budget_tokens or float("inf")
        spent = 0
        while self.waiting:
            seq = self.waiting[0]
            if len(self._running) + len(admitted) >= self.config.max_batch:
                break
            if seq.kv_ready_t > now:
                # migrated KV still on the wire (DCN transfer from a
                # dead engine's host tier): admitting before it lands
                # would prefill positions the migration covers —
                # head-of-line until the modeled transfer completes
                break
            need_tokens = len(seq.tokens)
            cached: List[int] = []
            if self.prefix_cache is not None and not seq.table.blocks:
                # peek first (no refcount bump): the hit only commits
                # once admission is certain, so a blocked head-of-line
                # request never leaks shared references
                cached, _ = self.prefix_cache.lookup(seq.tokens,
                                                     share=False)
            need_blocks = blocks_for_tokens(
                need_tokens + 1, self.allocator.block_size) - len(cached)
            if spent and spent + need_tokens > budget:
                break                      # budget spent: next round
            if not self.allocator.can_allocate(need_blocks):
                break                      # head-of-line until blocks free
            self.waiting.pop(0)
            seq.prefix_cached_tokens = 0
            seq.kv_fetched_host = 0
            seq.kv_fetched_peer = 0
            seq.kv_peer_fetch_s = 0.0
            shared: List[int] = []
            spills_before = (self.prefix_cache.spills
                             if self.prefix_cache is not None else 0)
            if self.prefix_cache is not None:
                from ..observability import metrics
                shared, n_cached = self.prefix_cache.lookup(seq.tokens)
                if shared:
                    seq.table.attach_shared(shared)
                    seq.prefix_cached_tokens = n_cached
                    # tier attribution: the engine charges the
                    # spill_fetch stall for promoted blocks
                    seq.kv_fetched_host = \
                        self.prefix_cache.last_host_fetched
                    seq.kv_fetched_peer = \
                        self.prefix_cache.last_peer_fetched
                    seq.kv_peer_fetch_s = \
                        self.prefix_cache.last_peer_fetch_s
                    metrics.inc("serving_prefix_hits_total")
                    metrics.inc("serving_prefix_hit_blocks_total",
                                len(shared))
                else:
                    metrics.inc("serving_prefix_misses_total")
            try:
                seq.table.ensure_capacity(need_tokens + 1)
            except OutOfBlocksError:
                # the can_allocate check above counted reclaimable
                # cached blocks as headroom — but THIS request's own
                # cached prefix may be exactly that headroom, and the
                # commit share just pinned it (refcount 2 = no longer
                # reclaimable). Undo the hit and put the request back
                # at the head: it stays head-of-line until real blocks
                # free up, nothing is lost or leaked.
                if shared:
                    self.allocator.free(shared)
                seq.table.blocks = []
                seq.prefix_cached_tokens = 0
                self.waiting.insert(0, seq)
                break
            if self.prefix_cache is not None:
                # publish the prompt's full blocks NOW, not after
                # prefill: a same-round sibling with the same system
                # prompt can then share them — every admitted
                # sequence's prefill scatters before any decode reads,
                # so the registered blocks' KV exists by first use
                self.prefix_cache.insert(seq.request.prompt,
                                         seq.table.blocks,
                                         len(seq.request.prompt))
                spilled = self.prefix_cache.spills - spills_before
                if spilled:
                    # this admission's allocations forced cold cached
                    # blocks down to the host tier — join key is the
                    # request whose admission applied the pressure
                    _flight_record(event="kv_spill", req=seq.req_id,
                                   tid=seq.trace_id, t=now,
                                   engine=self.engine_id,
                                   blocks=spilled)
            spent += need_tokens
            admitted.append(seq)
            _flight_record(event="admit", req=seq.req_id,
                           tid=seq.trace_id, t=now, tokens=need_tokens,
                           engine=self.engine_id,
                           blocks=len(seq.table.blocks),
                           shared_blocks=(len(seq.table.blocks)
                                          - need_blocks) or None)
        return admitted

    def mark_running(self, seq: Sequence) -> None:
        seq.state = SeqState.RUNNING
        self._running.append(seq)

    # -- decode-step block reservation ----------------------------------
    def reserve_decode_slots(self, seqs: Optional[List[Sequence]] = None,
                             now: Optional[float] = None,
                             slots: Optional[List[int]] = None
                             ) -> List[Sequence]:
        """Make sure every sequence in ``seqs`` (default: all running)
        has block slots for the token(s) the next decode step appends
        — ``slots[i]`` per sequence (default 1; a speculative verify
        round reserves ``1 + len(drafts)``) — evicting LIFO on
        exhaustion. Returns the evicted sequences (already requeued).
        ``now`` stamps the eviction spans."""
        victims: List[Sequence] = []
        todo = list(self._running) if seqs is None else list(seqs)
        want = [1] * len(todo) if slots is None else \
            [max(1, int(s)) for s in slots]
        if len(want) != len(todo):
            raise ValueError("slots must parallel seqs")
        i = 0
        while i < len(todo):
            seq = todo[i]
            if seq.state is not SeqState.RUNNING:
                i += 1      # evicted while reserving an earlier seq
                continue
            try:
                seq.table.ensure_capacity(seq.num_cached + want[i])
                i += 1
            except OutOfBlocksError:
                victim = self._running[-1]
                self._evict(victim, now=now)
                victims.append(victim)
                if victim is seq:
                    continue    # re-check the same index (list shrank)
        return victims

    def _evict(self, seq: Sequence, now: Optional[float] = None) -> None:
        self._running.remove(seq)
        seq.table.release()
        seq.evictions += 1
        self.total_evictions += 1
        _flight_record(event="evict", req=seq.req_id, tid=seq.trace_id,
                       t=now, engine=self.engine_id,
                       evictions=seq.evictions)
        # front of the queue: preempted work resumes before new arrivals
        self.requeue_front(seq, now=now, cause="evict")

    def requeue_corrupt(self, seq: Sequence,
                        now: Optional[float] = None) -> None:
        """Pull a RUNNING sequence whose block table can no longer be
        trusted (chaos ``corrupt_block_table``, a real scribble): the
        table is REBOUND to a fresh empty one instead of released —
        freeing corrupted ids could double-free a live block. The
        caller must rebuild the allocator's free list from the
        surviving tables (``BlockAllocator.rebuild_free_list``)."""
        self._running.remove(seq)
        seq.rebind(self.allocator)
        seq.recoveries += 1
        self.requeue_front(seq, now=now, cause="corrupt")

    # -- completion ------------------------------------------------------
    def finish(self, seq: Sequence, now: float = 0.0) -> None:
        self._running.remove(seq)
        seq.table.release()
        seq.state = SeqState.FINISHED
        seq.finish_t = now
        self.finished.append(seq)
        self._note_slo(seq, now)
        _flight_record(event="finish", req=seq.req_id, tid=seq.trace_id,
                       t=now, engine=self.engine_id,
                       tokens=len(seq.generated))

    # -- SLO accounting --------------------------------------------------
    def _note_slo(self, seq: Sequence, now: float) -> None:
        """Evaluate the engine's SLO targets against one FINISHED
        request (reliability.slo opt-in): TTFT, TPOT, e2e — all on the
        caller's clock, so the verdicts are as deterministic as the
        clock. Per-dimension verdicts and the good/bad totals flow
        through the metrics plane; the burn-rate gauge follows."""
        slo = self.reliability.slo
        if slo is None:
            return
        from ..observability import metrics
        arrival = seq.request.arrival_t
        first = seq.first_token_t if seq.first_token_t is not None else now
        gen = len(seq.generated)
        dims = {
            "ttft": (slo.ttft_target_s, first - arrival),
            "tpot": (slo.tpot_target_s,
                     (now - first) / (gen - 1) if gen > 1 else 0.0),
            "e2e": (slo.e2e_target_s, now - arrival),
        }
        good = True
        for name, (target, value) in dims.items():
            if target is None:
                continue
            ok = value <= target
            good = good and ok
            metrics.inc("serving_slo_checks_total", slo=name,
                        verdict="good" if ok else "bad")
        self._note_slo_verdict(good)

    def _note_slo_verdict(self, good: bool) -> None:
        from ..observability import metrics
        slo = self.reliability.slo
        if good:
            self.slo_good += 1
            metrics.inc("serving_slo_good_total")
        else:
            self.slo_bad += 1
            metrics.inc("serving_slo_bad_total")
        total = self.slo_good + self.slo_bad
        bad_frac = self.slo_bad / total if total else 0.0
        metrics.set_gauge("serving_slo_burn_rate",
                          bad_frac / slo.error_budget)

    # -- bucket shape of the current batch -------------------------------
    def decode_bucket(self, seqs: Optional[List[Sequence]] = None
                      ) -> Tuple[int, int]:
        """(batch_bucket, page_bucket) for the NEXT decode step over
        ``seqs`` (default: all running) — the compiled-program cache
        key; the engine passes the ready subset."""
        seqs = self._running if seqs is None else seqs
        n = len(seqs)
        pages = max((len(s.table.blocks) for s in seqs), default=1)
        return (self.config.batch_bucket(max(n, 1)),
                self.config.page_bucket(max(pages, 1)))
