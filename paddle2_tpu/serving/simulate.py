"""Deterministic discrete-event serving simulation (cost x rate).

Wall clocks are banned from every perf gate in this repo (gVisor/CI
sandboxes make them noise), so the serving bench drives the REAL
engine — real scheduler, real paged blocks, real compiled decode
programs producing real tokens — under a VIRTUAL clock: each decode
step advances time by the step's modeled cost (XLA ``cost_analysis``
FLOPs/bytes through the PR 7 :class:`StepCost` rate model), and
arrivals come from a seeded Poisson trace. Everything downstream
(tokens/s, TTFT percentiles, queueing) is a pure function of
(program costs, trace seed) — bit-stable across runs and machines.

Two lanes, per the prefill/decode disaggregation design: admitted
prompts are prefilled on the PREFILL lane (its own clock — a separate
instance in a real disaggregated deployment) and join the decode
batch when that lane finishes them; the decode clock only ever pays
decode-step costs, so a long prefill cannot stall token production
for running sequences.

The baseline (:func:`simulate_predictor_baseline`) models today's
``paddle.inference.Predictor`` loop — one request at a time, prefill
then token-by-token decode at batch 1 — over the SAME trace and the
same cost primitives. The bench gates continuous batching at >= 3x
its aggregate tokens/s.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

__all__ = ["poisson_trace", "ServingSimReport", "simulate_serving",
           "simulate_predictor_baseline", "cost_seconds"]


def poisson_trace(n_requests: int, rate_per_s: float,
                  prompt_lens, gen_tokens, vocab: int, seed: int = 0
                  ) -> List[dict]:
    """Seeded synthetic heavy-traffic trace: exponential interarrivals
    at ``rate_per_s``, prompt lengths/gen budgets cycled from the
    given lists, token ids uniform over ``vocab``. Deterministic in
    ``seed``."""
    rng = np.random.default_rng(seed)
    t = 0.0
    out = []
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate_per_s))
        plen = int(prompt_lens[i % len(prompt_lens)])
        out.append({
            "arrival_t": t,
            "prompt": rng.integers(0, vocab, size=plen).tolist(),
            "max_new_tokens": int(gen_tokens[i % len(gen_tokens)]),
        })
    return out


def cost_seconds(cost: Optional[Dict[str, float]],
                 fallback_s: float = 1e-3) -> float:
    """XLA cost dict -> modeled seconds: ``max(compute, memory)``
    under the chip rate model (CPU falls back to the fixed nominal
    figures in ``cost_model.CHIP_PEAKS`` — deterministic everywhere).
    ``fallback_s`` covers backends that expose no cost analysis."""
    if not cost or not cost.get("flops"):
        return fallback_s
    from ..observability.cost_model import StepCost
    sc = StepCost(flops=cost.get("flops", 0.0),
                  hbm_bytes=cost.get("bytes accessed", 0.0))
    return sc.step_time_modeled_s()


@dataclass
class ServingSimReport:
    total_tokens: int = 0
    makespan_s: float = 0.0
    tokens_per_s: float = 0.0
    ttft_s: List[float] = field(default_factory=list)
    p99_ttft_s: float = 0.0
    mean_ttft_s: float = 0.0
    decode_steps: int = 0
    evictions: int = 0
    kv_high_water_bytes: int = 0
    contiguous_cache_bytes: int = 0
    kv_ratio: float = 0.0
    decode_programs: int = 0
    program_budget: int = 0
    mean_batch_occupancy: float = 0.0

    def finalize(self, first_arrival: float, last_finish: float):
        self.makespan_s = max(last_finish - first_arrival, 1e-12)
        self.tokens_per_s = self.total_tokens / self.makespan_s
        if self.ttft_s:
            self.p99_ttft_s = float(np.percentile(self.ttft_s, 99))
            self.mean_ttft_s = float(np.mean(self.ttft_s))
        return self


def simulate_serving(engine, trace: List[dict],
                     max_steps: int = 100_000) -> ServingSimReport:
    """Drive ``engine`` through ``trace`` under the virtual clock.
    Requests are submitted at their arrival times; the report carries
    every gated quantity. The engine does REAL compute — final tokens
    are available via ``engine.sequence(rid).generated``."""
    pending = sorted(trace, key=lambda r: r["arrival_t"])
    first_arrival = pending[0]["arrival_t"] if pending else 0.0
    decode_clock = float(first_arrival)
    prefill_clock = 0.0
    evictions_before = engine.scheduler.total_evictions
    submitted: List[int] = []
    occupancy: List[float] = []
    rep = ServingSimReport()

    def submit_due(now: float):
        while pending and pending[0]["arrival_t"] <= now:
            r = pending.pop(0)
            submitted.append(engine.submit(
                r["prompt"], r["max_new_tokens"],
                arrival_t=r["arrival_t"]))

    for _ in range(max_steps):
        submit_due(decode_clock)
        if engine.idle() and not pending:
            break

        def lane_ready(info):
            # prefill lane: starts no earlier than the admission
            # instant or the lane's previous completion
            nonlocal prefill_clock
            start = max(prefill_clock, decode_clock,
                        info["seq"].request.arrival_t)
            prefill_clock = start + cost_seconds(info["cost"])
            return prefill_clock

        engine.admit_and_prefill(decode_clock, ready_at_fn=lane_ready)

        step = engine.decode_once(decode_clock)
        if step is not None:
            decode_clock += cost_seconds(step["cost"])
            occupancy.append(step["n_active"]
                             / engine.scheduler.config.max_batch)
        else:
            # nothing ready: jump to the next event (arrival or a
            # prefill completing on its lane)
            nxt = []
            if pending:
                nxt.append(pending[0]["arrival_t"])
            nxt.extend(getattr(s, "ready_at", 0.0)
                       for s in engine.scheduler.running())
            if not nxt:
                if engine.scheduler.waiting:
                    raise RuntimeError(
                        "head-of-line request can never be admitted "
                        "(prompt needs more blocks than the pool has)")
                break
            decode_clock = max(decode_clock, min(nxt)) + 1e-9
    else:
        raise RuntimeError(f"simulation did not converge in "
                           f"{max_steps} steps")

    finished = [engine.sequence(rid) for rid in submitted]
    last_finish = max((s.finish_t or 0.0) for s in finished) \
        if finished else 0.0
    # every generated token counts — including each request's FIRST
    # token, produced by its prefill (the baseline counts all of
    # max_new_tokens too; counting only decode-step tokens would bias
    # the throughput ratio against continuous batching)
    rep.total_tokens = sum(len(s.generated) for s in finished)
    # from the scheduler's own ledger, not per-step info dicts: an
    # eviction that empties the ready batch aborts the step and would
    # otherwise go uncounted
    rep.evictions = engine.scheduler.total_evictions - evictions_before
    rep.ttft_s = [max(0.0, s.first_token_t - s.request.arrival_t)
                  for s in finished if s.first_token_t is not None]
    rep.decode_steps = engine.decode_steps
    rep.kv_high_water_bytes = engine.kv_high_water_bytes()
    rep.contiguous_cache_bytes = engine.contiguous_cache_bytes()
    rep.kv_ratio = (rep.kv_high_water_bytes
                    / max(rep.contiguous_cache_bytes, 1))
    rep.decode_programs = engine.num_decode_programs
    rep.program_budget = engine.program_budget
    rep.mean_batch_occupancy = float(np.mean(occupancy)) if occupancy \
        else 0.0
    return rep.finalize(first_arrival, last_finish)


def simulate_predictor_baseline(engine, trace: List[dict]
                                ) -> ServingSimReport:
    """The one-request-at-a-time ``create_predictor`` loop over the
    SAME trace and cost primitives: serve requests in arrival order,
    each paying its full prefill then ``max_new_tokens - 1`` decode
    steps at batch 1, next request waits. Uses a throwaway decode
    build at bucket (1, max pages) for the step cost so the gated
    engine's program census stays untouched."""
    from .block_cache import blocks_for_tokens
    runner = engine.runner
    bs = engine.cache.block_size
    max_pages = blocks_for_tokens(engine.max_model_len, bs)
    # lower (never execute) a batch-1 decode for its cost analysis
    b1 = runner._build_decode(1, max_pages, bs)
    import jax
    import jax.numpy as jnp
    aval = lambda shape, dt: jax.ShapeDtypeStruct(shape, jnp.dtype(dt))
    shape = engine.cache.k.shape
    b1_cost = runner._cost_of(b1, (
        [aval(tuple(t.shape), t._data.dtype) for t in runner._state],
        aval(shape, engine.cache.dtype), aval(shape, engine.cache.dtype),
        aval((1, 1), "int32"), aval((1,), "int32"),
        aval((1, max_pages), "int32")))
    decode_s = cost_seconds(b1_cost)

    rep = ServingSimReport()
    t = 0.0
    first_arrival = min(r["arrival_t"] for r in trace) if trace else 0.0
    last_finish = 0.0
    for r in sorted(trace, key=lambda x: x["arrival_t"]):
        n = len(r["prompt"])
        padded = runner.prefill_padded_len(n)
        pcost = runner.prefill_cost(padded)
        if pcost is None:
            # make sure the prefill program exists so its cost does
            runner.prefill(list(r["prompt"]))
            pcost = runner.prefill_cost(padded)
        start = max(t, r["arrival_t"])
        first_tok = start + cost_seconds(pcost)
        rep.ttft_s.append(first_tok - r["arrival_t"])
        t = first_tok + max(0, r["max_new_tokens"] - 1) * decode_s
        rep.total_tokens += r["max_new_tokens"]
        last_finish = t
    # contiguous max-seq-len cache, one slot: that IS the predictor's
    # KV footprint per in-flight request
    rep.kv_high_water_bytes = engine.cache.contiguous_bytes(
        1, engine.max_model_len)
    rep.contiguous_cache_bytes = rep.kv_high_water_bytes
    rep.kv_ratio = 1.0
    return rep.finalize(first_arrival, last_finish)
