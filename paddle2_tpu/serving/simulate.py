"""Deterministic discrete-event serving simulation (cost x rate).

Wall clocks are banned from every perf gate in this repo (gVisor/CI
sandboxes make them noise), so the serving bench drives the REAL
engine — real scheduler, real paged blocks, real compiled decode
programs producing real tokens — under a VIRTUAL clock: each decode
step advances time by the step's modeled cost (XLA ``cost_analysis``
FLOPs/bytes through the PR 7 :class:`StepCost` rate model), and
arrivals come from a seeded Poisson trace. Everything downstream
(tokens/s, TTFT percentiles, queueing) is a pure function of
(program costs, trace seed) — bit-stable across runs and machines.

Two lanes, per the prefill/decode disaggregation design: admitted
prompts are prefilled on the PREFILL lane (its own clock — a separate
instance in a real disaggregated deployment) and join the decode
batch when that lane finishes them; the decode clock only ever pays
decode-step costs, so a long prefill cannot stall token production
for running sequences.

The baseline (:func:`simulate_predictor_baseline`) models today's
``paddle.inference.Predictor`` loop — one request at a time, prefill
then token-by-token decode at batch 1 — over the SAME trace and the
same cost primitives. The bench gates continuous batching at >= 3x
its aggregate tokens/s.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

__all__ = ["poisson_trace", "diurnal_poisson_trace", "ServingSimReport",
           "simulate_serving", "simulate_predictor_baseline",
           "cost_seconds",
           "EngineFailoverRouter", "RouterSimReport", "simulate_router",
           "FleetKVRegistry"]


def poisson_trace(n_requests: int, rate_per_s: float,
                  prompt_lens, gen_tokens, vocab: int, seed: int = 0
                  ) -> List[dict]:
    """Seeded synthetic heavy-traffic trace: exponential interarrivals
    at ``rate_per_s``, prompt lengths/gen budgets cycled from the
    given lists, token ids uniform over ``vocab``. Deterministic in
    ``seed``."""
    rng = np.random.default_rng(seed)
    t = 0.0
    out = []
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate_per_s))
        plen = int(prompt_lens[i % len(prompt_lens)])
        out.append({
            "arrival_t": t,
            "prompt": rng.integers(0, vocab, size=plen).tolist(),
            "max_new_tokens": int(gen_tokens[i % len(gen_tokens)]),
        })
    return out


def diurnal_poisson_trace(n_requests: int, day_s: float,
                          prompt_lens, gen_tokens, vocab: int,
                          seed: int = 0, peak_hour: float = 14.0,
                          trough_frac: float = 0.25,
                          cohorts=()) -> List[dict]:
    """Seeded NON-homogeneous Poisson trace over one simulated day:
    arrival intensity follows a raised-cosine diurnal curve (peak at
    ``peak_hour`` local, trough at ``trough_frac`` of the peak rate),
    sampled by inverting the numeric rate integral — order statistics
    of a day-long inhomogeneous Poisson process conditioned on
    ``n_requests`` arrivals. Deterministic in ``seed``.

    ``cohorts`` optionally injects shared-prefix sessions (the
    fleet-KV exercise): each entry is ``(prefix_tokens, arrival_ts)``
    and adds one request per listed arrival time whose prompt starts
    with that exact prefix — same-prefix requests route by affinity
    and exercise the prefix-cache / host-tier / migration ladder.
    Every request carries a ``session`` id; arrivals come out sorted."""
    rng = np.random.default_rng(seed)
    hours = np.linspace(0.0, 24.0, 1441)
    rate = trough_frac + (1.0 - trough_frac) * 0.5 * (
        1.0 + np.cos(2.0 * np.pi * (hours - peak_hour) / 24.0))
    cum = np.concatenate(
        ([0.0], np.cumsum((rate[1:] + rate[:-1]) * 0.5)))
    cum /= cum[-1]
    u = np.sort(rng.random(n_requests))
    arrivals = np.interp(u, cum, hours) / 24.0 * day_s
    out = []
    for i, t in enumerate(arrivals):
        plen = int(prompt_lens[i % len(prompt_lens)])
        out.append({
            "arrival_t": float(t),
            "prompt": rng.integers(0, vocab, size=plen).tolist(),
            "max_new_tokens": int(gen_tokens[i % len(gen_tokens)]),
            "session": f"day-{i}",
        })
    for c, (prefix, times) in enumerate(cohorts):
        for j, t in enumerate(times):
            out.append({
                "arrival_t": float(t),
                "prompt": list(prefix),
                "max_new_tokens": int(gen_tokens[j % len(gen_tokens)]),
                "session": f"cohort-{c}-{j}",
            })
    out.sort(key=lambda r: (r["arrival_t"], r["session"]))
    return out


def cost_seconds(cost: Optional[Dict[str, float]],
                 fallback_s: float = 1e-3) -> float:
    """XLA cost dict -> modeled seconds: ``max(compute, memory)``
    under the chip rate model (CPU falls back to the fixed nominal
    figures in ``cost_model.CHIP_PEAKS`` — deterministic everywhere).
    ``fallback_s`` covers backends that expose no cost analysis."""
    if not cost or not cost.get("flops"):
        return fallback_s
    from ..observability.cost_model import StepCost
    sc = StepCost(flops=cost.get("flops", 0.0),
                  hbm_bytes=cost.get("bytes accessed", 0.0))
    return sc.step_time_modeled_s()


@dataclass
class ServingSimReport:
    total_tokens: int = 0
    makespan_s: float = 0.0
    tokens_per_s: float = 0.0
    ttft_s: List[float] = field(default_factory=list)
    p99_ttft_s: float = 0.0
    mean_ttft_s: float = 0.0
    decode_steps: int = 0
    evictions: int = 0
    kv_high_water_bytes: int = 0
    contiguous_cache_bytes: int = 0
    kv_ratio: float = 0.0
    decode_programs: int = 0
    program_budget: int = 0
    mean_batch_occupancy: float = 0.0
    # total modeled FLOPs executed (prefills + decode steps): the
    # denominator of the deterministic tracing-overhead gate
    modeled_flops: float = 0.0
    # CoW prefix-cache economics (ISSUE 14): KV blocks actually
    # MATERIALIZED (allocator handouts, not shares) — the bytes/request
    # figure the shared-prefix bench gate divides down
    kv_allocated_blocks: int = 0
    kv_allocated_bytes: int = 0
    kv_bytes_per_request: float = 0.0
    prefix_hits: int = 0
    prefix_misses: int = 0
    # speculative-decoding ledger: drafts the verify pass kept/killed
    spec_accepted: int = 0
    spec_rejected: int = 0
    spec_acceptance: float = 0.0

    def finalize(self, first_arrival: float, last_finish: float):
        self.makespan_s = max(last_finish - first_arrival, 1e-12)
        self.tokens_per_s = self.total_tokens / self.makespan_s
        if self.ttft_s:
            self.p99_ttft_s = float(np.percentile(self.ttft_s, 99))
            self.mean_ttft_s = float(np.mean(self.ttft_s))
        proposed = self.spec_accepted + self.spec_rejected
        self.spec_acceptance = (self.spec_accepted / proposed
                                if proposed else 0.0)
        return self


def simulate_serving(engine, trace: List[dict],
                     max_steps: int = 100_000) -> ServingSimReport:
    """Drive ``engine`` through ``trace`` under the virtual clock.
    Requests are submitted at their arrival times; the report carries
    every gated quantity. The engine does REAL compute — final tokens
    are available via ``engine.sequence(rid).generated``."""
    pending = sorted(trace, key=lambda r: r["arrival_t"])
    first_arrival = pending[0]["arrival_t"] if pending else 0.0
    decode_clock = float(first_arrival)
    prefill_clock = 0.0
    evictions_before = engine.scheduler.total_evictions
    alloc_before = engine.allocator.total_allocated
    spec_before = (engine.spec_accepted, engine.spec_rejected)
    pfx_before = ((engine.prefix_cache.hits, engine.prefix_cache.misses)
                  if engine.prefix_cache is not None else (0, 0))
    submitted: List[int] = []
    occupancy: List[float] = []
    rep = ServingSimReport()

    def submit_due(now: float):
        while pending and pending[0]["arrival_t"] <= now:
            r = pending.pop(0)
            submitted.append(engine.submit(
                r["prompt"], r["max_new_tokens"],
                arrival_t=r["arrival_t"]))

    for _ in range(max_steps):
        submit_due(decode_clock)
        if engine.idle() and not pending:
            break

        def lane_ready(info):
            # prefill lane: starts no earlier than the admission
            # instant or the lane's previous completion. The lane pays
            # the CHARGED cost when the engine provides one (KV
            # tiering scales the charge to the uncached prompt tail;
            # absent tiering the key is absent and this is info["cost"]
            # bit-for-bit).
            nonlocal prefill_clock
            start = max(prefill_clock, decode_clock,
                        info["seq"].request.arrival_t)
            prefill_clock = start + cost_seconds(
                info.get("charged_cost") or info["cost"])
            return prefill_clock

        infos = engine.admit_and_prefill(decode_clock,
                                         ready_at_fn=lane_ready)
        rep.modeled_flops += sum(
            (i["cost"] or {}).get("flops", 0.0) for i in infos)

        step = engine.decode_once(decode_clock)
        if step is not None:
            decode_clock += cost_seconds(step["cost"])
            rep.modeled_flops += (step["cost"] or {}).get("flops", 0.0)
            occupancy.append(step["n_active"]
                             / engine.scheduler.config.max_batch)
        else:
            # nothing ready: jump to the next event (arrival or a
            # prefill completing on its lane)
            nxt = []
            if pending:
                nxt.append(pending[0]["arrival_t"])
            nxt.extend(getattr(s, "ready_at", 0.0)
                       for s in engine.scheduler.running())
            if not nxt:
                if engine.scheduler.waiting:
                    raise RuntimeError(
                        "head-of-line request can never be admitted "
                        "(prompt needs more blocks than the pool has)")
                break
            decode_clock = max(decode_clock, min(nxt)) + 1e-9
    else:
        raise RuntimeError(f"simulation did not converge in "
                           f"{max_steps} steps")

    finished = [engine.sequence(rid) for rid in submitted]
    last_finish = max((s.finish_t or 0.0) for s in finished) \
        if finished else 0.0
    # every generated token counts — including each request's FIRST
    # token, produced by its prefill (the baseline counts all of
    # max_new_tokens too; counting only decode-step tokens would bias
    # the throughput ratio against continuous batching)
    rep.total_tokens = sum(len(s.generated) for s in finished)
    # from the scheduler's own ledger, not per-step info dicts: an
    # eviction that empties the ready batch aborts the step and would
    # otherwise go uncounted
    rep.evictions = engine.scheduler.total_evictions - evictions_before
    rep.ttft_s = [max(0.0, s.first_token_t - s.request.arrival_t)
                  for s in finished if s.first_token_t is not None]
    rep.decode_steps = engine.decode_steps
    rep.kv_high_water_bytes = engine.kv_high_water_bytes()
    rep.contiguous_cache_bytes = engine.contiguous_cache_bytes()
    rep.kv_ratio = (rep.kv_high_water_bytes
                    / max(rep.contiguous_cache_bytes, 1))
    rep.decode_programs = engine.num_decode_programs
    rep.program_budget = engine.program_budget
    rep.mean_batch_occupancy = float(np.mean(occupancy)) if occupancy \
        else 0.0
    rep.kv_allocated_blocks = (engine.allocator.total_allocated
                               - alloc_before)
    rep.kv_allocated_bytes = engine.cache.bytes_for_blocks(
        rep.kv_allocated_blocks)
    rep.kv_bytes_per_request = (rep.kv_allocated_bytes
                                / max(len(submitted), 1))
    rep.spec_accepted = engine.spec_accepted - spec_before[0]
    rep.spec_rejected = engine.spec_rejected - spec_before[1]
    if engine.prefix_cache is not None:
        rep.prefix_hits = engine.prefix_cache.hits - pfx_before[0]
        rep.prefix_misses = engine.prefix_cache.misses - pfx_before[1]
    return rep.finalize(first_arrival, last_finish)


# ------------------------------------------------- fleet-global KV tier
class FleetKVRegistry:
    """Fleet-global KV coordination: the peer tier over DCN plus the
    prefix advertisement the affinity router consults (ROADMAP 2(e)).

    Wires every engine's :class:`PrefixCache` with a peer-fetch
    source: on a local HBM+host miss, the registry scans the other
    alive engines for the longest contiguous run of the missing chain
    (HBM or host tier), prices the DCN transfer with the PR 14
    alpha+beta :class:`LinkModel`, prices the re-prefill of the same
    tokens with the engine's own XLA cost model, and fetches ONLY
    when the modeled transfer beats the modeled recompute — a pure
    deterministic cost-model decision, gated both ways by
    ``bench.py --fleet-kv``. The same LinkModel prices failover KV
    migration (:meth:`EngineFailoverRouter._maybe_migrate`)."""

    def __init__(self, engines: List, link=None):
        from ..observability.cost_model import (
            LinkModel, DEFAULT_DCN_LATENCY_US)
        self.engines = list(engines)
        # alpha+beta: DCN latency term ON (a prefix fetch is one RPC;
        # pricing it latency-free would make tiny transfers free and
        # break the gated-both-ways decision)
        self.link = link if link is not None else LinkModel(
            dcn_latency_us=DEFAULT_DCN_LATENCY_US)
        self.peer_fetches = 0
        self.peer_fetch_blocks = 0
        self.peer_declined = 0
        for e in self.engines:
            if e.prefix_cache is not None:
                e.prefix_cache.set_peer_source(self._source_for(e))

    def modeled_prefill_s(self, eng, n_tokens: int,
                          total_tokens: int) -> float:
        """Modeled seconds re-prefilling ``n_tokens`` of a
        ``total_tokens`` prompt would cost on ``eng`` — the same
        linear-in-tokens charge the tiering clock uses, so the
        fetch-vs-recompute decision and the clock agree."""
        if total_tokens <= 0 or n_tokens <= 0:
            return 0.0
        padded = eng.runner.prefill_padded_len(total_tokens)
        full = cost_seconds(eng.runner.prefill_cost(padded))
        return full * (n_tokens / total_tokens)

    def _source_for(self, eng):
        def fetch(missing_keys):
            # longest contiguous run any alive peer can serve
            best, best_n = None, 0
            for peer in self.engines:
                if peer is eng or peer.failed \
                        or peer.prefix_cache is None:
                    continue
                pc = peer.prefix_cache
                n = 0
                for key in missing_keys:
                    if key in pc._entries or (
                            pc.host_tier is not None
                            and key in pc.host_tier):
                        n += 1
                    else:
                        break
                if n > best_n:
                    best, best_n = peer, n
            if best is None or best_n == 0:
                return [], 0.0
            bs = eng.cache.block_size
            total = len(missing_keys[-1])    # keys ARE token prefixes
            t_fetch = self.link.seconds(
                best_n * eng.cache.block_bytes, axes=("dcn",))
            t_prefill = self.modeled_prefill_s(eng, best_n * bs, total)
            if t_fetch >= t_prefill:
                self.peer_declined += 1
                return [], 0.0
            payloads = best.prefix_cache.export_chain(
                list(missing_keys[:best_n]))
            if not payloads:
                return [], 0.0
            self.peer_fetches += 1
            self.peer_fetch_blocks += len(payloads)
            # export may stop short (corrupt host entry): charge the
            # transfer pro-rata for what actually moved
            return payloads, t_fetch * (len(payloads) / best_n)
        return fetch


# ------------------------------------------------- multi-engine failover
class EngineFailoverRouter:
    """Deterministic multi-engine router with session affinity, health
    probes, and engine-failure recovery (ROADMAP 2(c)/(d)).

    Routing: a request with a ``session`` sticks to its session's
    engine (KV/prefix locality); otherwise the least-loaded alive
    engine wins (ties: lowest index). Health is probed on a fixed
    virtual-clock cadence using the ``fault_tolerance/health.py``
    idiom — each sweep yields one :class:`HealthReport` per engine —
    and a probe that finds an engine dead triggers failover: every
    in-flight sequence is harvested from the dead engine's host-side
    token logs (``recover_inflight``) and adopted at the FRONT of a
    healthy engine's queue, preserving admission order. Re-prefill of
    the token log reproduces the lost KV exactly, so recovered
    requests complete token-for-token identical to a fault-free run.
    MTTR (engine death -> every recovered sequence re-prefilled and
    producing tokens again) is measured on the virtual clock and gated
    by ``bench.py --serving-reliability``."""

    def __init__(self, engines: List, probe_interval_s: float = 1e-3,
                 kv_registry: Optional[FleetKVRegistry] = None):
        if not engines:
            raise ValueError("need at least one engine")
        if not probe_interval_s > 0.0:
            # maybe_probe advances in probe_interval_s steps; a
            # non-positive cadence would spin forever
            raise ValueError(
                f"probe_interval_s must be > 0, got {probe_interval_s}")
        self.engines = list(engines)
        for i, e in enumerate(self.engines):
            e.engine_id = i
        # fleet KV tier: enables prefix-affinity routing and
        # migrate-instead-of-re-prefill failover (None = PR 11
        # behavior, bit-for-bit)
        self.kv_registry = kv_registry
        self.kv_migrated_blocks = 0
        self.migrations = 0
        self.migrations_declined = 0
        self.probe_interval_s = float(probe_interval_s)
        # anchored lazily to the FIRST maybe_probe stamp: a fixed 0.0
        # anchor would make a first call at a large `now` spin through
        # one catch-up sweep per interval since time zero
        self._next_probe_t: Optional[float] = None
        self._affinity: Dict[object, int] = {}
        self._seqs: Dict[int, object] = {}      # global rid -> Sequence
        self._home: Dict[int, int] = {}         # global rid -> engine idx
        self._next_rid = 0
        self._handled_failures: set = set()
        self.failovers: List[dict] = []
        self.probes = 0

    # -- routing ---------------------------------------------------------
    def alive(self) -> List[int]:
        return [i for i, e in enumerate(self.engines) if not e.failed]

    def _load(self, idx: int) -> int:
        e = self.engines[idx]
        return len(e.scheduler.running()) + len(e.scheduler.waiting)

    def _pick(self, session=None, prompt=None) -> int:
        alive = self.alive()
        if not alive:
            from .reliability import EngineFailedError
            raise EngineFailedError("no alive engine to route to")
        if session is not None:
            idx = self._affinity.get(session)
            if idx is not None and not self.engines[idx].failed:
                return idx
        if prompt is not None and self.kv_registry is not None:
            # prefix affinity: the engine already holding the longest
            # cached prefix (HBM or host tier) serves the request —
            # ties break least-loaded then lowest index; zero cached
            # tokens everywhere falls through to least-loaded
            cached = {
                i: self.engines[i].prefix_cache.cached_prefix_tokens(
                    prompt)
                for i in alive
                if self.engines[i].prefix_cache is not None}
            if cached:
                idx = min(cached,
                          key=lambda i: (-cached[i], self._load(i), i))
                if cached[idx] > 0:
                    if session is not None:
                        self._affinity[session] = idx
                    return idx
        idx = min(alive, key=lambda i: (self._load(i), i))
        if session is not None:
            self._affinity[session] = idx
        return idx

    def submit(self, prompt, max_new_tokens: int, arrival_t: float = 0.0,
               session=None, priority: Optional[int] = None,
               deadline_s: Optional[float] = None) -> int:
        """Route one request; returns a router-global request id.
        Typed rejections (queue full, prompt too long) propagate from
        the target engine."""
        idx = self._pick(session, prompt=prompt
                         if self.kv_registry is not None else None)
        rid = self._next_rid
        local = self.engines[idx].submit(
            prompt, max_new_tokens, arrival_t=arrival_t,
            priority=priority, deadline_s=deadline_s,
            trace_id=rid)      # fleet-global span identity
        self._next_rid += 1
        self._seqs[rid] = self.engines[idx].sequence(local)
        self._home[rid] = idx
        return rid

    def sequence(self, rid: int):
        return self._seqs[rid]

    def home_of(self, rid: int) -> int:
        """Engine index currently serving ``rid`` (updated when a
        failover re-homes the sequence)."""
        return self._home[rid]

    # -- health + failover -----------------------------------------------
    def maybe_probe(self, now: float) -> None:
        """Run every probe sweep whose cadence stamp has passed; the
        cadence anchors at the first call's ``now``."""
        if self._next_probe_t is None:
            self._next_probe_t = float(now)
        while now >= self._next_probe_t:
            self.probe(self._next_probe_t)
            self._next_probe_t += self.probe_interval_s

    def probe(self, now: float) -> List:
        """One health sweep (``health.py`` HealthReport idiom); a
        newly-dead engine fails over HERE — detection latency is part
        of the gated MTTR."""
        from ..distributed.fault_tolerance.health import HealthReport
        self.probes += 1
        reports = []
        for i, e in enumerate(self.engines):
            rep = HealthReport(ok=not e.failed,
                               reason=e.fail_reason or "",
                               probe="serving_engine")
            reports.append(rep)
            if not rep.ok and i not in self._handled_failures:
                # no adopter alive -> leave the failure UNhandled (and
                # the dead engine's sequences unharvested, so nothing
                # is lost); a later sweep retries once capacity exists
                if not self.alive():
                    continue
                self._handled_failures.add(i)
                self._failover(i, now)
        return reports

    def _failover(self, dead_idx: int, now: float) -> None:
        from ..observability import metrics
        from .reliability import flight_record
        alive = self.alive()
        if not alive:
            from .reliability import EngineFailedError
            raise EngineFailedError(
                "no alive engine to adopt recovered sequences")
        dead = self.engines[dead_idx]
        recovered = dead.recover_inflight()
        # drop dead-engine affinity; sessions re-pin on next submit
        for sess in [s for s, i in self._affinity.items()
                     if i == dead_idx]:
            del self._affinity[sess]
        # assign targets in recovery order, least-loaded alive first
        # with each assignment counted (so a big recovery spreads
        # across the fleet instead of piling on one engine), then
        # adopt per target in REVERSE so front-insertion preserves the
        # original in-flight order
        loads = {i: self._load(i) for i in alive}
        targets: Dict[int, List] = {}
        for seq in recovered:
            idx = min(alive, key=lambda i: (loads[i], i))
            loads[idx] += 1
            targets.setdefault(idx, []).append(seq)
        rid_of = {id(s): rid for rid, s in self._seqs.items()}
        for idx, seqs in sorted(targets.items()):
            eng = self.engines[idx]
            # adopt() front-inserts ever-ADMITTED work and APPENDS
            # never-admitted arrivals (normal bounded submit), so the
            # two groups need opposite iteration orders to preserve
            # the original admission/FIFO order on the adopter
            inflight = [s for s in seqs if eng.scheduler._in_flight(s)]
            fresh = [s for s in seqs if not eng.scheduler._in_flight(s)]
            # migrate-instead-of-re-prefill (tentpole c): before the
            # adopter re-queues each sequence, pull its surviving
            # host-tier KV across DCN when the modeled transfer beats
            # the modeled re-prefill — the migrate span lands BEFORE
            # the adopt span at the same stamp so the decomposition
            # charges migration_stall then reopens the failover wait
            for seq in inflight + fresh:
                self._maybe_migrate(dead, eng, seq, now)
            for seq in list(reversed(inflight)) + fresh:
                eng.adopt(seq, now=now)
                if id(seq) in rid_of:       # keep home_of() truthful
                    self._home[rid_of[id(seq)]] = idx
        metrics.inc("serving_failovers_total")
        flight_record(
            event="failover", engine=dead_idx, t=now,
            failed_t=dead.failed_t, recovered=len(recovered),
            tids=[s.trace_id for s in recovered
                  if s.trace_id is not None] or None,
            targets={str(k): len(v) for k, v in targets.items()})
        self.failovers.append({
            "engine": dead_idx, "failed_t": dead.failed_t,
            "detected_t": now, "seqs": recovered,
            "recovered": len(recovered), "recovered_t": None,
            "mttr_s": None})

    def _maybe_migrate(self, dead, eng, seq, now: float) -> int:
        """KV migration instead of re-prefill (tentpole c): the dead
        engine's HBM is gone, but its host-DRAM spill tier survives
        the device. If it holds a leading run of ``seq``'s prefix
        chain, price moving those blocks to the adopter over DCN
        against the modeled re-prefill of the same tokens; migrate
        only when the transfer wins. Migrated payloads are CRC-checked
        into fresh blocks in the ADOPTER's prefix cache (cache-owned,
        refcount 1), and ``seq.kv_ready_t`` holds the sequence out of
        admission until the modeled transfer lands — so admission
        re-prefills only the tail, and the decomposition's
        migration-stall component is exact. A chaos-dropped or corrupt
        transfer degrades to plain re-prefill. Returns blocks moved."""
        if self.kv_registry is None:
            return 0
        tier = getattr(dead, "host_tier", None)
        pc = eng.prefix_cache
        if tier is None or len(tier) == 0 or pc is None:
            return 0
        from ..distributed.fault_tolerance import chaos
        from ..observability import metrics
        from .block_cache import OutOfBlocksError
        from .reliability import flight_record
        keys = pc._keys(seq.tokens)
        n = 0
        for key in keys:
            if key in pc._entries:
                # adopter already holds it (an earlier migration of a
                # shared prefix) — admission's lookup will hit it
                n += 1
                continue
            if key in tier:
                n += 1
            else:
                break
        todo = [k for k in keys[:n] if k not in pc._entries]
        if not todo:
            return 0
        t_mig = self.kv_registry.link.seconds(
            len(todo) * eng.cache.block_bytes, axes=("dcn",))
        t_re = self.kv_registry.modeled_prefill_s(
            eng, len(todo) * eng.cache.block_size, len(seq.tokens))
        if t_mig >= t_re:
            # short context / cheap recompute: re-prefill wins, by
            # the same model the clock charges — counted, not silent
            self.migrations_declined += 1
            flight_record(event="migrate_declined",
                          engine=eng.engine_id, tid=seq.trace_id,
                          t=now, blocks=len(todo),
                          src=getattr(dead, "engine_id", None))
            return 0
        if chaos.maybe_drop_migration():
            # injected transfer loss: fall back to re-prefill — the
            # token log still reproduces the KV exactly
            flight_record(event="migration_dropped",
                          engine=eng.engine_id, tid=seq.trace_id,
                          t=now, blocks=len(todo),
                          chaos="drop_migration")
            return 0
        moved = 0
        for key in todo:
            payload = tier.get(key)     # CRC-verified; corrupt -> None
            if payload is None:
                break                   # tail re-prefills
            try:
                nb = eng.allocator.allocate(1)[0]
            except OutOfBlocksError:
                break
            eng._kv_scatter_block(nb, payload[0], payload[1])
            pc._entries[key] = nb       # cache-owned: allocate's ref
            pc._lru[key] = nb
            tier.pop(key)               # one tier owns a prefix
            moved += 1
        if not moved:
            return 0
        stall = t_mig * (moved / len(todo))
        seq.kv_ready_t = max(getattr(seq, "kv_ready_t", 0.0),
                             now + stall)
        self.migrations += 1
        self.kv_migrated_blocks += moved
        metrics.inc("serving_kv_migrated_blocks_total", moved)
        flight_record(event="migrate", engine=eng.engine_id,
                      tid=seq.trace_id, t=now, dur=stall,
                      blocks=moved,
                      src=getattr(dead, "engine_id", None))
        return moved

    def note_recovery(self, now: float) -> None:
        """Stamp MTTR for failovers whose every recovered sequence has
        SETTLED: re-prefilled (RUNNING with a fresh ``ready_at``),
        finished, or shed by the adopter's admission control (a
        never-admitted fresh arrival refused at adoption counts as
        settled — recovery is about resuming ACCEPTED work)."""
        from .scheduler import SeqState
        settled = (SeqState.RUNNING, SeqState.FINISHED, SeqState.SHED)
        for fo in self.failovers:
            if fo["recovered_t"] is not None:
                continue
            seqs = fo["seqs"]
            if all(s.state in settled for s in seqs):
                done = max((getattr(s, "ready_at", now) for s in seqs
                            if s.state is not SeqState.SHED),
                           default=now)
                fo["recovered_t"] = done
                fo["mttr_s"] = done - (fo["failed_t"] or 0.0)

    @property
    def mttr_s(self) -> float:
        """Worst recovered-failover MTTR (0.0 when none)."""
        vals = [fo["mttr_s"] for fo in self.failovers
                if fo["mttr_s"] is not None]
        return max(vals) if vals else 0.0


@dataclass
class RouterSimReport(ServingSimReport):
    engines: int = 0
    completed: int = 0
    submitted: int = 0
    rejected: int = 0
    shed: int = 0
    failovers: int = 0
    recovered_seqs: int = 0
    mttr_s: float = 0.0
    probes: int = 0
    hot_swaps: int = 0
    rids: List[int] = field(default_factory=list)
    # fleet-global KV ladder (ISSUE 16)
    kv_spilled_blocks: int = 0
    kv_fetch_host_blocks: int = 0
    kv_fetch_peer_blocks: int = 0
    kv_migrated_blocks: int = 0
    kv_migrations: int = 0
    kv_migrations_declined: int = 0
    kv_host_tier_blocks: int = 0


def simulate_router(router: EngineFailoverRouter, trace: List[dict],
                    max_rounds: int = 100_000,
                    on_round=None) -> RouterSimReport:
    """Drive a fleet through ``trace`` under ONE virtual clock,
    lockstep: each round, every alive engine admits+prefills (its own
    prefill lane) and runs at most one decode step; the clock advances
    by the SLOWEST engine's step cost that round (engines run in
    parallel, so a round costs its straggler — conservative for every
    gated quantity). Health probes fire on their cadence at round
    boundaries; a chaos ``kill_engine`` fires inside ``decode_once``
    and surfaces as ``EngineFailedError``, which the loop absorbs —
    the ROUTER only learns at its next probe, so detection latency is
    inside the gated MTTR. Trace entries may carry ``session``,
    ``priority``, ``deadline_s``; typed rejections are counted, not
    raised. ``on_round(router, clock, round_idx)`` is the
    deterministic hook the hot-swap drill uses to stage rollouts."""
    from .reliability import EngineFailedError, RequestRejected
    from .scheduler import SeqState

    pending = sorted(trace, key=lambda r: r["arrival_t"])
    first_arrival = pending[0]["arrival_t"] if pending else 0.0
    clock = float(first_arrival)
    prefill_clocks = [0.0] * len(router.engines)
    rep = RouterSimReport(engines=len(router.engines))
    # per-engine snapshots so the report carries THIS simulation's
    # deltas, not lifetime totals (an engine warmed by a prior sim
    # must not inflate the gated figures)
    before = {id(e): (e.allocator.total_allocated, e.spec_accepted,
                      e.spec_rejected,
                      (e.prefix_cache.hits, e.prefix_cache.misses)
                      if e.prefix_cache is not None else (0, 0),
                      (e.prefix_cache.spills,
                       e.prefix_cache.host_fetches,
                       e.prefix_cache.peer_fetches)
                      if e.prefix_cache is not None else (0, 0, 0))
              for e in router.engines}
    mig_before = (router.kv_migrated_blocks, router.migrations,
                  router.migrations_declined)

    def submit_due(now: float):
        while pending and pending[0]["arrival_t"] <= now:
            r = pending.pop(0)
            try:
                rid = router.submit(
                    r["prompt"], r["max_new_tokens"],
                    arrival_t=r["arrival_t"], session=r.get("session"),
                    priority=r.get("priority"),
                    deadline_s=r.get("deadline_s"))
                rep.rids.append(rid)
                rep.submitted += 1
            except (RequestRejected, EngineFailedError):
                # typed rejections are COUNTED, not raised — including
                # "no alive engine to route to" under total fleet death
                rep.rejected += 1

    def lane_ready_fn(idx: int, now: float):
        def lane_ready(info):
            start = max(prefill_clocks[idx], now,
                        info["seq"].request.arrival_t)
            # charged_cost (KV tiering: pay for the uncached tail
            # only) when present; identical to info["cost"] otherwise
            prefill_clocks[idx] = start + cost_seconds(
                info.get("charged_cost") or info["cost"])
            return prefill_clocks[idx]
        return lane_ready

    for round_idx in range(max_rounds):
        router.maybe_probe(clock)
        submit_due(clock)
        if on_round is not None:
            on_round(router, clock, round_idx)
        costs = []
        for idx in router.alive():
            eng = router.engines[idx]
            try:
                infos = eng.admit_and_prefill(
                    clock, ready_at_fn=lane_ready_fn(idx, clock))
                rep.modeled_flops += sum(
                    (i["cost"] or {}).get("flops", 0.0) for i in infos)
                step = eng.decode_once(clock)
            except EngineFailedError:
                continue            # died this round; next probe sees it
            if step is not None:
                costs.append(cost_seconds(step["cost"]))
                rep.modeled_flops += (step["cost"] or {}).get(
                    "flops", 0.0)
        router.note_recovery(clock)
        if not router.alive():
            # total fleet death: nothing can ever serve the remainder
            rep.rejected += len(pending)
            pending.clear()
            break
        busy = any(not router.engines[i].idle() for i in router.alive())
        undetected = [i for i, e in enumerate(router.engines)
                      if e.failed and i not in router._handled_failures]
        if not pending and not busy and not undetected:
            break
        if costs:
            clock += max(costs)
        else:
            # legible stall diagnosis (simulate_serving's twin): an
            # idle engine whose head-of-line prompt needs more blocks
            # than its whole pool holds can never make progress
            from .block_cache import blocks_for_tokens
            for i in router.alive():
                eng = router.engines[i]
                w = eng.scheduler.waiting
                if w and not eng.scheduler.running() and not pending:
                    need = blocks_for_tokens(
                        len(w[0].tokens) + 1, eng.cache.block_size)
                    if need > eng.allocator.num_blocks - 1:
                        raise RuntimeError(
                            "head-of-line request can never be "
                            "admitted (prompt needs more blocks than "
                            "the pool has)")
            nxt = [r["arrival_t"] for r in pending[:1]]
            if (undetected or busy) and router._next_probe_t is not None:
                nxt.append(router._next_probe_t)
            for i in router.alive():
                nxt.extend(getattr(s, "ready_at", 0.0) for s in
                           router.engines[i].scheduler.running())
                # a migrated sequence is admission-gated until its KV
                # transfer lands — wake at that stamp or the gate
                # deadlocks an otherwise-idle fleet
                nxt.extend(
                    s.kv_ready_t
                    for s in router.engines[i].scheduler.waiting
                    if getattr(s, "kv_ready_t", 0.0) > clock)
            if not nxt:
                break
            clock = max(clock, min(nxt)) + 1e-9
    else:
        raise RuntimeError(
            f"router simulation did not converge in {max_rounds} rounds")

    seqs = [router.sequence(rid) for rid in rep.rids]
    done = [s for s in seqs if s.state is SeqState.FINISHED]
    rep.completed = len(done)
    rep.shed = sum(e.scheduler.total_shed for e in router.engines)
    rep.total_tokens = sum(len(s.generated) for s in done)
    rep.ttft_s = [max(0.0, s.first_token_t - s.request.arrival_t)
                  for s in done if s.first_token_t is not None]
    rep.decode_steps = sum(e.decode_steps for e in router.engines)
    rep.evictions = sum(e.scheduler.total_evictions
                        for e in router.engines)
    for e in router.engines:
        alloc0, acc0, rej0, (hit0, miss0), (sp0, fh0, fp0) = before[id(e)]
        blocks = e.allocator.total_allocated - alloc0
        rep.kv_allocated_blocks += blocks
        rep.kv_allocated_bytes += e.cache.bytes_for_blocks(blocks)
        rep.spec_accepted += e.spec_accepted - acc0
        rep.spec_rejected += e.spec_rejected - rej0
        if e.prefix_cache is not None:
            rep.prefix_hits += e.prefix_cache.hits - hit0
            rep.prefix_misses += e.prefix_cache.misses - miss0
            rep.kv_spilled_blocks += e.prefix_cache.spills - sp0
            rep.kv_fetch_host_blocks += e.prefix_cache.host_fetches - fh0
            rep.kv_fetch_peer_blocks += e.prefix_cache.peer_fetches - fp0
        if getattr(e, "host_tier", None) is not None:
            rep.kv_host_tier_blocks += len(e.host_tier)
    rep.kv_migrated_blocks = router.kv_migrated_blocks - mig_before[0]
    rep.kv_migrations = router.migrations - mig_before[1]
    rep.kv_migrations_declined = (router.migrations_declined
                                  - mig_before[2])
    rep.kv_bytes_per_request = (rep.kv_allocated_bytes
                                / max(rep.submitted, 1))
    rep.failovers = len(router.failovers)
    rep.recovered_seqs = sum(fo["recovered"] for fo in router.failovers)
    rep.mttr_s = router.mttr_s
    rep.probes = router.probes
    alive = router.alive()
    rep.decode_programs = sum(router.engines[i].num_decode_programs
                              for i in alive)
    rep.program_budget = sum(router.engines[i].program_budget
                             for i in alive)
    last_finish = max((s.finish_t or 0.0) for s in done) if done else 0.0
    return rep.finalize(first_arrival, last_finish)


def simulate_predictor_baseline(engine, trace: List[dict]
                                ) -> ServingSimReport:
    """The one-request-at-a-time ``create_predictor`` loop over the
    SAME trace and cost primitives: serve requests in arrival order,
    each paying its full prefill then ``max_new_tokens - 1`` decode
    steps at batch 1, next request waits. Uses a throwaway decode
    build at bucket (1, max pages) for the step cost so the gated
    engine's program census stays untouched."""
    from .block_cache import blocks_for_tokens
    runner = engine.runner
    bs = engine.cache.block_size
    max_pages = blocks_for_tokens(engine.max_model_len, bs)
    # lower (never execute) a batch-1 decode for its cost analysis
    b1 = runner._build_decode(1, max_pages, bs)
    import jax
    import jax.numpy as jnp
    aval = lambda shape, dt: jax.ShapeDtypeStruct(shape, jnp.dtype(dt))
    shape = engine.cache.k.shape
    b1_cost = runner._cost_of(b1, (
        [aval(tuple(t.shape), t._data.dtype) for t in runner._state],
        aval(shape, engine.cache.dtype), aval(shape, engine.cache.dtype),
        aval((1, 1), "int32"), aval((1,), "int32"),
        aval((1, max_pages), "int32")))
    decode_s = cost_seconds(b1_cost)

    rep = ServingSimReport()
    t = 0.0
    first_arrival = min(r["arrival_t"] for r in trace) if trace else 0.0
    last_finish = 0.0
    for r in sorted(trace, key=lambda x: x["arrival_t"]):
        n = len(r["prompt"])
        padded = runner.prefill_padded_len(n)
        pcost = runner.prefill_cost(padded)
        if pcost is None:
            # make sure the prefill program exists so its cost does
            runner.prefill(list(r["prompt"]))
            pcost = runner.prefill_cost(padded)
        start = max(t, r["arrival_t"])
        first_tok = start + cost_seconds(pcost)
        rep.ttft_s.append(first_tok - r["arrival_t"])
        t = first_tok + max(0, r["max_new_tokens"] - 1) * decode_s
        rep.total_tokens += r["max_new_tokens"]
        last_finish = t
    # contiguous max-seq-len cache, one slot: that IS the predictor's
    # KV footprint per in-flight request
    rep.kv_high_water_bytes = engine.cache.contiguous_bytes(
        1, engine.max_model_len)
    rep.contiguous_cache_bytes = rep.kv_high_water_bytes
    rep.kv_ratio = 1.0
    return rep.finalize(first_arrival, last_finish)
