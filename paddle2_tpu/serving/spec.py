"""Speculative decoding: self-drafting n-gram proposals + in-program
verify-and-accept through the paged decode program (ISSUE 14 /
ROADMAP 2(b); Leviathan et al., "Fast Inference from Transformers via
Speculative Decoding").

Design — draft cheap, verify exact:

* **Draft** (host, free): :func:`ngram_draft` proposes up to ``k``
  continuation tokens by matching the sequence's own token log — the
  newest earlier occurrence of the last ``n`` tokens nominates what
  followed it ("prompt lookup" drafting: no draft model, no extra
  weights, deterministic). A custom ``draft_fn`` slots in for a real
  draft model (or the bench's fixed-acceptance oracle).
* **Verify** (device, one program): the engine feeds the pending true
  token plus the drafts as EXTRA BATCH ROWS of the SAME compiled
  paged-decode program — row ``i`` carries token ``i`` of the chunk at
  position ``p0 + i`` with the sequence's own block table, so every
  row runs the identical single-query-row attention a sequential
  decode would (the kernel is row-independent; per-row K/V scatters
  land before the attention reads them, and causal masking via the
  per-row context length keeps later drafts invisible to earlier
  rows). No new program shapes beyond a wider batch bucket — the
  program census stays inside the scheduler's bucket grid.
* **Accept** (:func:`accept_drafts`, host): greedy speculative
  acceptance — drafts are accepted while they match the model's own
  argmax continuation, then the model's next token rides along as the
  bonus. With greedy decoding this is EXACT by construction: the
  emitted stream is token-for-token the non-speculative stream, no
  matter how wrong the drafts are (wrong drafts only cost the wasted
  rows). The rejected tail's KV writes land past the accepted
  ``num_tokens`` and are overwritten before any later row can read
  them; its surplus blocks roll back via ``BlockTable.truncate``.

Throughput story (priced, not wall-clocked): decode is weight-bytes
bound, so a verify step over ``B * (k+1)`` rows costs barely more than
a plain ``B``-row step in the cost model while emitting
``1 + accepted`` tokens per sequence — ``bench.py
--serving-throughput`` gates the modeled tokens/s uplift at a fixed
70% acceptance rate against the non-speculative run, plus token-CRC
equality (the exactness half).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence as Seq, Tuple

__all__ = ["SpeculativeConfig", "ngram_draft", "accept_drafts"]


@dataclass
class SpeculativeConfig:
    """Knobs for the engine's speculative decode rounds.

    ``num_draft_tokens`` (k) bounds the chunk a verify round covers
    (``k + 1`` rows per sequence — the engine widens its batch-bucket
    ladder to ``max_batch * (k + 1)`` so the program census stays
    bounded). ``ngram`` is the self-draft match length.
    ``draft_fn(seq) -> List[int]`` overrides the drafter entirely
    (return at most ``num_draft_tokens`` proposals; empty list =
    plain 1-token decode for that sequence this round)."""
    num_draft_tokens: int = 3
    ngram: int = 2
    draft_fn: Optional[Callable] = None

    def __post_init__(self):
        if self.num_draft_tokens < 1:
            raise ValueError("num_draft_tokens must be >= 1")
        if self.ngram < 1:
            raise ValueError("ngram must be >= 1")


def ngram_draft(tokens: Seq[int], ngram: int, k: int) -> List[int]:
    """Self-draft by n-gram lookup: find the NEWEST earlier occurrence
    of the trailing ``ngram`` tokens in ``tokens`` and propose the up
    to ``k`` tokens that followed it. Deterministic, pure host. Empty
    when the log is too short or nothing matches."""
    toks = [int(t) for t in tokens]
    n = len(toks)
    if k < 1 or n <= ngram:
        return []
    pat = toks[-ngram:]
    # newest match first: recent continuations predict better
    for j in range(n - ngram - 1, -1, -1):
        if toks[j:j + ngram] == pat:
            return toks[j + ngram:j + ngram + k]
    return []


def accept_drafts(drafts: Seq[int], outs: Seq[int], budget: int
                  ) -> Tuple[List[int], int]:
    """Greedy verify: ``outs[i]`` is the model's argmax after
    consuming chunk row ``i`` (row 0 = the pending true token, row
    ``i >= 1`` = ``drafts[i-1]``). Accept drafts while
    ``drafts[i] == outs[i]`` — i.e. while the draft IS what the model
    would have emitted — then the next model output rides along as the
    bonus token. ``budget`` caps total emitted tokens (accepted +
    bonus), so a sequence never overshoots ``max_new_tokens``.
    Returns ``(accepted, bonus)``."""
    if budget < 1:
        raise ValueError("accept budget must be >= 1")
    accepted: List[int] = []
    for i, d in enumerate(drafts):
        if len(accepted) + 1 >= budget:
            break
        if int(d) == int(outs[i]):
            accepted.append(int(d))
        else:
            break
    return accepted, int(outs[len(accepted)])
