"""paddle.signal (reference python/paddle/signal.py: frame, overlap_add,
stft, istft) — jnp implementation through the op dispatcher."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .framework.tensor import Tensor
from .ops.dispatch import apply_op, ensure_tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """signal.py frame: slide windows of frame_length every hop_length."""
    t = ensure_tensor(x)

    def f(a):
        n = a.shape[axis]
        n_frames = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(n_frames) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        moved = jnp.moveaxis(a, axis, -1)
        framed = moved[..., idx]                       # [..., F, L]
        if axis != 0:
            # paddle layout axis=-1: [..., frame_length, num_frames]
            return jnp.swapaxes(framed, -1, -2)
        # paddle layout axis=0: [num_frames, frame_length, ...]
        return jnp.moveaxis(framed, (-2, -1), (0, 1))
    return apply_op("frame", f, (t,), {})


def overlap_add(x, hop_length, axis=-1, name=None):
    """signal.py overlap_add: inverse of frame (axis=-1 layout
    [..., frame_length, n_frames])."""
    t = ensure_tensor(x)

    def f(a):
        last_axis = axis != 0
        if not last_axis:
            # paddle axis=0 layout [F, L, ...] -> [..., L, F]
            a = jnp.moveaxis(a, (0, 1), (-1, -2))
        L, F = a.shape[-2], a.shape[-1]
        n = (F - 1) * hop_length + L
        idx = (jnp.arange(F) * hop_length)[:, None] + jnp.arange(L)[None]
        out = jnp.zeros(a.shape[:-2] + (n,), a.dtype)
        # one scatter-add over the [F, L] index matrix
        out = out.at[..., idx].add(jnp.swapaxes(a, -1, -2))
        if not last_axis:
            out = jnp.moveaxis(out, -1, 0)
        return out
    return apply_op("overlap_add", f, (t,), {})


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """signal.py stft parity; returns [..., n_fft//2+1, n_frames] complex."""
    t = ensure_tensor(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = ensure_tensor(window)._data if window is not None \
        else jnp.ones((win_length,), jnp.float32)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        win = jnp.pad(win, (pad, n_fft - win_length - pad))

    def f(a):
        if center:
            widths = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, widths, mode=pad_mode)
        n = a.shape[-1]
        n_frames = 1 + (n - n_fft) // hop_length
        starts = jnp.arange(n_frames) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = a[..., idx] * win                      # [..., F, n_fft]
        spec = jnp.fft.rfft(frames, axis=-1) if onesided \
            else jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.float32(n_fft))
        return jnp.swapaxes(spec, -1, -2)               # [..., bins, F]
    return apply_op("stft", f, (t,), {})


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """signal.py istft parity (overlap-add with window-square norm)."""
    t = ensure_tensor(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = ensure_tensor(window)._data if window is not None \
        else jnp.ones((win_length,), jnp.float32)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        win = jnp.pad(win, (pad, n_fft - win_length - pad))

    def f(a):
        spec = jnp.swapaxes(a, -1, -2)                  # [..., F, bins]
        if normalized:
            spec = spec * jnp.sqrt(jnp.float32(n_fft))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * win
        F = frames.shape[-2]
        n = (F - 1) * hop_length + n_fft
        idx = (jnp.arange(F) * hop_length)[:, None] \
            + jnp.arange(n_fft)[None]
        out = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        out = out.at[..., idx].add(frames)      # one scatter-add
        norm = jnp.zeros((n,), jnp.float32).at[idx].add(win * win)
        out = out / jnp.maximum(norm, 1e-8)
        if center:
            out = out[..., n_fft // 2: n - n_fft // 2]
        if length is not None:
            cur = out.shape[-1]
            if cur < length:  # frame grid rarely lands exactly on `length`
                widths = [(0, 0)] * (out.ndim - 1) + [(0, length - cur)]
                out = jnp.pad(out, widths)
            else:
                out = out[..., :length]
        return out
    return apply_op("istft", f, (t,), {})
