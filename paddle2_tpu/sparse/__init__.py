"""paddle.sparse (reference python/paddle/sparse/) — COO/CSR tensors.

TPU-native reality check: XLA has no sparse kernels; the MXU wants dense
tiles. Sparse tensors here are index+values containers (BCOO-style) whose
compute ops densify at the boundary — matching the reference's API while
keeping every op jit-compatible. For genuinely sparse workloads the
recommended TPU path is dense masking (the reference's own TPU guidance).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..ops.dispatch import ensure_tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "is_same_shape", "matmul", "add", "multiply",
           "relu", "to_dense"]


class SparseCooTensor:
    """COO container (reference sparse_coo_tensor contract)."""

    def __init__(self, indices, values, shape):
        self._indices = ensure_tensor(indices)   # [ndim, nnz]
        self._values = ensure_tensor(values)     # [nnz, ...]
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    def indices(self):
        return self._indices

    def values(self):
        return self._values

    def nnz(self) -> int:
        return int(self._values.shape[0])

    def to_dense(self) -> Tensor:
        dense = jnp.zeros(self._shape, self._values._data.dtype)
        idx = tuple(self._indices._data[i]
                    for i in range(self._indices.shape[0]))
        return Tensor(dense.at[idx].add(self._values._data))

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if len(self._shape) != 2:
            raise ValueError("to_sparse_csr expects a 2-D COO tensor")
        d = np.asarray(self.to_dense()._data)
        return _dense_to_csr(d)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self._shape}, "
                f"nnz={self.nnz()})")


class SparseCsrTensor:
    """CSR container."""

    def __init__(self, crows, cols, values, shape):
        self._crows = ensure_tensor(crows)
        self._cols = ensure_tensor(cols)
        self._values = ensure_tensor(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    def crows(self):
        return self._crows

    def cols(self):
        return self._cols

    def values(self):
        return self._values

    def nnz(self) -> int:
        return int(self._values.shape[0])

    def to_dense(self) -> Tensor:
        crows = np.asarray(self._crows._data)
        cols = np.asarray(self._cols._data)
        vals = self._values._data
        rows = np.repeat(np.arange(len(crows) - 1),
                         np.diff(crows).astype(int))
        dense = jnp.zeros(self._shape, vals.dtype)
        return Tensor(dense.at[rows, cols].add(vals))

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self._shape}, "
                f"nnz={self.nnz()})")


def _dense_to_csr(d: np.ndarray) -> SparseCsrTensor:
    rows, cols = np.nonzero(d)
    vals = d[rows, cols]
    crows = np.zeros(d.shape[0] + 1, np.int64)
    for r in rows:
        crows[r + 1] += 1
    crows = np.cumsum(crows)
    return SparseCsrTensor(crows, cols.astype(np.int64), vals, d.shape)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True) -> SparseCooTensor:
    ind = ensure_tensor(indices)
    val = ensure_tensor(values)
    if shape is None:
        mx = np.asarray(ind._data).max(axis=1) + 1
        shape = tuple(int(v) for v in mx)
    return SparseCooTensor(ind, val, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True) -> SparseCsrTensor:
    return SparseCsrTensor(crows, cols, values, shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def to_dense(x) -> Tensor:
    return x.to_dense() if hasattr(x, "to_dense") else ensure_tensor(x)


def matmul(x, y) -> Tensor:
    from ..ops.linalg import matmul as dense_matmul
    return dense_matmul(to_dense(x), to_dense(y))


def add(x, y):
    return to_dense(x) + to_dense(y)


def multiply(x, y):
    return to_dense(x) * to_dense(y)


def relu(x):
    from ..nn import functional as F
    return F.relu(to_dense(x))
