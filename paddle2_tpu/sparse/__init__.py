"""paddle.sparse (reference python/paddle/sparse/) — COO/CSR tensors.

TPU-native reality check: XLA has no sparse kernels; the MXU wants dense
tiles. Sparse tensors here are index+values containers (BCOO-style) whose
compute ops densify at the boundary — matching the reference's API while
keeping every op jit-compatible. For genuinely sparse workloads the
recommended TPU path is dense masking (the reference's own TPU guidance).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..ops.dispatch import ensure_tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "is_same_shape", "matmul", "add", "multiply",
           "relu", "to_dense"]


class SparseCooTensor:
    """COO container (reference sparse_coo_tensor contract)."""

    def __init__(self, indices, values, shape):
        self._indices = ensure_tensor(indices)   # [ndim, nnz]
        self._values = ensure_tensor(values)     # [nnz, ...]
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    def indices(self):
        return self._indices

    def values(self):
        return self._values

    def nnz(self) -> int:
        return int(self._values.shape[0])

    def to_dense(self) -> Tensor:
        dense = jnp.zeros(self._shape, self._values._data.dtype)
        idx = tuple(self._indices._data[i]
                    for i in range(self._indices.shape[0]))
        return Tensor(dense.at[idx].add(self._values._data))

    def to_sparse_csr(self) -> "SparseCsrTensor":
        if len(self._shape) != 2:
            raise ValueError("to_sparse_csr expects a 2-D COO tensor")
        d = np.asarray(self.to_dense()._data)
        return _dense_to_csr(d)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self._shape}, "
                f"nnz={self.nnz()})")


class SparseCsrTensor:
    """CSR container."""

    def __init__(self, crows, cols, values, shape):
        self._crows = ensure_tensor(crows)
        self._cols = ensure_tensor(cols)
        self._values = ensure_tensor(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    def crows(self):
        return self._crows

    def cols(self):
        return self._cols

    def values(self):
        return self._values

    def nnz(self) -> int:
        return int(self._values.shape[0])

    def to_dense(self) -> Tensor:
        crows = np.asarray(self._crows._data)
        cols = np.asarray(self._cols._data)
        vals = self._values._data
        rows = np.repeat(np.arange(len(crows) - 1),
                         np.diff(crows).astype(int))
        dense = jnp.zeros(self._shape, vals.dtype)
        return Tensor(dense.at[rows, cols].add(vals))

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self._shape}, "
                f"nnz={self.nnz()})")


def _dense_to_csr(d: np.ndarray) -> SparseCsrTensor:
    rows, cols = np.nonzero(d)
    vals = d[rows, cols]
    crows = np.zeros(d.shape[0] + 1, np.int64)
    for r in rows:
        crows[r + 1] += 1
    crows = np.cumsum(crows)
    return SparseCsrTensor(crows, cols.astype(np.int64), vals, d.shape)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True) -> SparseCooTensor:
    ind = ensure_tensor(indices)
    val = ensure_tensor(values)
    if shape is None:
        mx = np.asarray(ind._data).max(axis=1) + 1
        shape = tuple(int(v) for v in mx)
    return SparseCooTensor(ind, val, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True) -> SparseCsrTensor:
    return SparseCsrTensor(crows, cols, values, shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def to_dense(x) -> Tensor:
    return x.to_dense() if hasattr(x, "to_dense") else ensure_tensor(x)


def matmul(x, y) -> Tensor:
    from ..ops.linalg import matmul as dense_matmul
    return dense_matmul(to_dense(x), to_dense(y))


def add(x, y):
    return to_dense(x) + to_dense(y)


def multiply(x, y):
    return to_dense(x) * to_dense(y)


def relu(x):
    from ..nn import functional as F
    return F.relu(to_dense(x))


# ------------------------------------------------------------------ r5
# value-wise unary ops: all zero-preserving (f(0)=0), so they transform
# VALUES in place and keep the sparsity structure — the same contract as
# the reference's sparse unary kernels (phi/kernels/sparse/unary_*).

def _same_structure(x, new_values):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x._indices, new_values, x._shape)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x._crows, x._cols, new_values, x._shape)
    return new_values


def _unary(opname, jfn):
    def op(x, name=None):
        if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
            from ..ops.dispatch import apply_op
            vals = apply_op(f"sparse_{opname}", jfn, (x._values,), {})
            return _same_structure(x, vals)
        from ..ops.dispatch import apply_op
        return apply_op(opname, jfn, (ensure_tensor(x),), {})
    op.__name__ = opname
    op.__doc__ = f"sparse.{opname}: value-wise (zero-preserving)."
    return op


abs = _unary("abs", jnp.abs)          # noqa: A001
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
deg2rad = _unary("deg2rad", jnp.deg2rad)
expm1 = _unary("expm1", jnp.expm1)
log1p = _unary("log1p", jnp.log1p)
neg = _unary("neg", jnp.negative)
rad2deg = _unary("rad2deg", jnp.rad2deg)
sin = _unary("sin", jnp.sin)
sinh = _unary("sinh", jnp.sinh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
tan = _unary("tan", jnp.tan)
tanh = _unary("tanh", jnp.tanh)


def pow(x, factor, name=None):  # noqa: A001
    from ..ops.dispatch import apply_op
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        vals = apply_op("sparse_pow", lambda v: jnp.power(v, factor),
                        (x._values,), {})
        return _same_structure(x, vals)
    return apply_op("pow", lambda v: jnp.power(v, factor),
                    (ensure_tensor(x),), {})


def isnan(x, name=None):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        return _same_structure(x, Tensor(jnp.isnan(x._values._data)))
    return Tensor(jnp.isnan(ensure_tensor(x)._data))


def cast(x, index_dtype=None, value_dtype=None, name=None):
    """sparse.cast: change index/value dtypes, keep structure."""
    if not isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        raise TypeError("sparse.cast expects a sparse tensor")
    vals = (Tensor(x._values._data.astype(value_dtype))
            if value_dtype else x._values)
    if isinstance(x, SparseCooTensor):
        idx = (Tensor(x._indices._data.astype(index_dtype))
               if index_dtype else x._indices)
        return SparseCooTensor(idx, vals, x._shape)
    crows = (Tensor(x._crows._data.astype(index_dtype))
             if index_dtype else x._crows)
    cols = (Tensor(x._cols._data.astype(index_dtype))
            if index_dtype else x._cols)
    return SparseCsrTensor(crows, cols, vals, x._shape)


def coalesce(x, name=None):
    """sparse.coalesce: sum duplicate COO entries (host; data-dependent
    output size, like the reference's kernel)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("coalesce expects a COO tensor")
    idx = np.asarray(x._indices._data)
    vals = np.asarray(x._values._data)
    flat = np.ravel_multi_index(idx, x._shape)
    uniq, inv = np.unique(flat, return_inverse=True)
    out_vals = np.zeros((len(uniq),) + vals.shape[1:], vals.dtype)
    np.add.at(out_vals, inv, vals)
    out_idx = np.stack(np.unravel_index(uniq, x._shape))
    return SparseCooTensor(Tensor(jnp.asarray(out_idx)),
                           Tensor(jnp.asarray(out_vals)), x._shape)


def subtract(x, y, name=None):
    return to_dense(x) - to_dense(y)


def divide(x, y, name=None):
    return to_dense(x) / to_dense(y)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """sparse.sum: over values (axis=None) or via the dense view."""
    from ..ops.dispatch import apply_op
    if axis is None:
        return apply_op("sparse_sum", jnp.sum, (x._values,), {})
    d = to_dense(x)
    return apply_op("sparse_sum",
                    lambda a: jnp.sum(a, axis=axis, keepdims=keepdim),
                    (d,), {})


def mv(x, vec, name=None):
    """sparse.mv: CSR/COO matrix @ dense vector without densifying the
    matrix — gather + segment-sum over the nonzeros (the TPU-friendly
    formulation of spmv)."""
    from ..ops.dispatch import apply_op
    v = ensure_tensor(vec)
    if isinstance(x, SparseCsrTensor):
        crows = np.asarray(x._crows._data)
        rows = np.repeat(np.arange(len(crows) - 1),
                         np.diff(crows).astype(int))
        rows_j = jnp.asarray(rows)
        cols = x._cols

        def fn(vals, cols_, vd):
            prod = vals * jnp.take(vd, cols_)
            import jax
            return jax.ops.segment_sum(prod, rows_j,
                                       num_segments=x._shape[0])
        return apply_op("sparse_mv", fn, (x._values, cols, v), {})
    if isinstance(x, SparseCooTensor):
        rows_t, cols_t = x._indices._data[0], x._indices._data[1]

        def fn(vals, vd):
            import jax
            prod = vals * jnp.take(vd, cols_t)
            return jax.ops.segment_sum(prod, rows_t,
                                       num_segments=x._shape[0])
        return apply_op("sparse_mv", fn, (x._values, v), {})
    raise TypeError("mv expects a sparse matrix")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """sparse.addmm: beta*input + alpha*(x @ y)."""
    return ensure_tensor(to_dense(input)) * beta + matmul(x, y) * alpha


def masked_matmul(x, y, mask, name=None):
    """sparse.masked_matmul: (x @ y) evaluated ONLY at mask's sparsity
    pattern (SDDMM). Gathers the needed row/col pairs, so the dense
    product never materializes."""
    from ..ops.dispatch import apply_op
    xd = ensure_tensor(x)
    yd = ensure_tensor(y)
    if isinstance(mask, SparseCsrTensor):
        crows = np.asarray(mask._crows._data)
        rows = jnp.asarray(np.repeat(np.arange(len(crows) - 1),
                                     np.diff(crows).astype(int)))
        cols_t = mask._cols

        def fn(a, b, cols_):
            av = jnp.take(a, rows, axis=0)
            bv = jnp.take(b.T, cols_, axis=0)
            return jnp.sum(av * bv, axis=-1)
        vals = apply_op("sparse_sddmm", fn, (xd, yd, cols_t), {})
        return SparseCsrTensor(mask._crows, mask._cols, vals, mask._shape)
    if isinstance(mask, SparseCooTensor):
        rows_t = mask._indices._data[0]
        cols_t = mask._indices._data[1]

        def fn(a, b):
            av = jnp.take(a, rows_t, axis=0)
            bv = jnp.take(b.T, cols_t, axis=0)
            return jnp.sum(av * bv, axis=-1)
        vals = apply_op("sparse_sddmm", fn, (xd, yd), {})
        return SparseCooTensor(mask._indices, vals, mask._shape)
    raise TypeError("mask must be sparse")


def mask_as(x, mask, name=None):
    """sparse.mask_as: sample dense x at mask's sparsity pattern."""
    xd = ensure_tensor(x)._data
    if isinstance(mask, SparseCooTensor):
        idx = tuple(mask._indices._data[i]
                    for i in range(mask._indices.shape[0]))
        return SparseCooTensor(mask._indices, Tensor(xd[idx]),
                               mask._shape)
    if isinstance(mask, SparseCsrTensor):
        crows = np.asarray(mask._crows._data)
        rows = np.repeat(np.arange(len(crows) - 1),
                         np.diff(crows).astype(int))
        vals = xd[jnp.asarray(rows), mask._cols._data]
        return SparseCsrTensor(mask._crows, mask._cols, Tensor(vals),
                               mask._shape)
    raise TypeError("mask must be sparse")


def reshape(x, shape, name=None):
    """sparse.reshape: remap COO indices through the flat index."""
    if isinstance(x, SparseCooTensor):
        flat = np.ravel_multi_index(np.asarray(x._indices._data),
                                    x._shape)
        new_idx = np.stack(np.unravel_index(flat, tuple(shape)))
        return SparseCooTensor(Tensor(jnp.asarray(new_idx)), x._values,
                               tuple(shape))
    d = np.asarray(to_dense(x)._data).reshape(shape)
    return _dense_to_csr(d) if len(shape) == 2 else \
        sparse_coo_from_dense(Tensor(jnp.asarray(d)))


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    """sparse.slice via the dense view (host; output nnz data-dependent)."""
    d = np.asarray(to_dense(x)._data)
    sl = [np.s_[:]] * d.ndim
    for ax, s, e in zip(axes, starts, ends):
        sl[int(ax)] = np.s_[int(s):int(e)]
    out = d[tuple(sl)]
    if isinstance(x, SparseCsrTensor) and out.ndim == 2:
        return _dense_to_csr(out)
    return sparse_coo_from_dense(Tensor(jnp.asarray(out)))


def sparse_coo_from_dense(d, stop_gradient=True) -> SparseCooTensor:
    """to_sparse_coo on a dense Tensor (host nonzero scan)."""
    arr = np.asarray(ensure_tensor(d)._data)
    idx = np.stack(np.nonzero(arr))
    vals = arr[tuple(idx)]
    return SparseCooTensor(Tensor(jnp.asarray(idx)),
                           Tensor(jnp.asarray(vals)), arr.shape)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """sparse.pca_lowrank via the dense view's SVD."""
    d = to_dense(x)._data.astype(jnp.float32)
    if center:
        d = d - jnp.mean(d, axis=0, keepdims=True)
    u, s, vt = jnp.linalg.svd(d, full_matrices=False)
    if q is not None:
        u, s, vt = u[:, :q], s[:q], vt[:q]
    return Tensor(u), Tensor(s), Tensor(vt.T)


from . import nn  # noqa: F401,E402

__all__ += ["abs", "asin", "asinh", "atan", "atanh", "cast", "coalesce",
            "deg2rad", "divide", "expm1", "isnan", "log1p", "mask_as",
            "masked_matmul", "mv", "neg", "pca_lowrank", "pow",
            "rad2deg", "reshape", "sin", "sinh", "slice", "sqrt",
            "square", "subtract", "sum", "tan", "tanh", "addmm", "nn"]


def transpose(x, perm, name=None):
    """sparse.transpose: permute COO index rows (structure-only)."""
    if isinstance(x, SparseCooTensor):
        idx = x._indices._data[jnp.asarray(perm)]
        shape = tuple(x._shape[p] for p in perm)
        return SparseCooTensor(Tensor(idx), x._values, shape)
    d = np.asarray(to_dense(x)._data).transpose(perm)
    return _dense_to_csr(d) if d.ndim == 2 else \
        sparse_coo_from_dense(Tensor(jnp.asarray(d)))


__all__ += ["transpose"]
