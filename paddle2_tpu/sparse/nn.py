"""paddle.sparse.nn (reference python/paddle/sparse/nn/): layers over
sparse tensors. Activations/norms are value-wise (structure preserved);
convolutions run the dense lax.conv on the densified block — XLA has no
sparse conv kernels and the MXU wants dense tiles, so submanifold
semantics are enforced by re-masking to the input's active sites
(the defining property of SubmConv, sparse/gpu/conv_kernel.cu)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SyncBatchNorm", "Conv2D", "Conv3D", "SubmConv2D",
           "SubmConv3D", "MaxPool3D"]


def _values_op(x, fn):
    from . import SparseCooTensor, SparseCsrTensor, _same_structure
    from ..ops.dispatch import apply_op
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        return _same_structure(x, apply_op("sparse_act", fn,
                                           (x._values,), {}))
    from ..ops.dispatch import ensure_tensor
    return apply_op("sparse_act", fn, (ensure_tensor(x),), {})


class ReLU(Layer):
    def forward(self, x):
        return _values_op(x, lambda v: jnp.maximum(v, 0))


class ReLU6(Layer):
    def forward(self, x):
        return _values_op(x, lambda v: jnp.clip(v, 0, 6))


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        s = self._slope
        return _values_op(x, lambda v: jnp.where(v >= 0, v, s * v))


class Softmax(Layer):
    """sparse softmax over the last dim of the CSR rows: normalizes each
    row's NONZERO entries (reference sparse softmax semantics)."""

    def __init__(self, axis=-1):
        super().__init__()

    def forward(self, x):
        from . import SparseCsrTensor
        if not isinstance(x, SparseCsrTensor):
            raise TypeError("sparse.nn.Softmax expects a CSR tensor")
        crows = np.asarray(x._crows._data)
        rows = jnp.asarray(np.repeat(np.arange(len(crows) - 1),
                                     np.diff(crows).astype(int)))
        from ..ops.dispatch import apply_op

        def fn(vals):
            n = x._shape[0]
            row_max = jax.ops.segment_max(vals, rows, num_segments=n)
            e = jnp.exp(vals - jnp.take(row_max, rows))
            denom = jax.ops.segment_sum(e, rows, num_segments=n)
            return e / jnp.take(denom, rows)

        vals = apply_op("sparse_softmax", fn, (x._values,), {})
        return SparseCsrTensor(x._crows, x._cols, vals, x._shape)


class BatchNorm(Layer):
    """sparse BatchNorm: normalizes the VALUES' channel dim (channels
    last in sparse layout)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 name=None):
        super().__init__()
        self._eps = epsilon
        self._momentum = momentum
        self.weight = self.create_parameter([num_features],
                                            attr=weight_attr,
                                            default_initializer=None)
        import jax.numpy as _j
        self.weight._replace_data(_j.ones([num_features], _j.float32))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self._mean = _j.zeros([num_features], _j.float32)
        self._var = _j.ones([num_features], _j.float32)

    def forward(self, x):
        from . import SparseCooTensor
        from ..ops.dispatch import apply_op
        if not isinstance(x, SparseCooTensor):
            raise TypeError("sparse.nn.BatchNorm expects a COO tensor")
        eps = self._eps
        training = self.training

        def fn(vals, w, b):
            if training:
                mean = jnp.mean(vals, axis=0)
                var = jnp.var(vals, axis=0)
            else:
                mean, var = self._mean, self._var
            return (vals - mean) / jnp.sqrt(var + eps) * w + b

        vals = apply_op("sparse_bn", fn,
                        (x._values, self.weight, self.bias), {})
        if self.training:
            v = np.asarray(x._values._data)
            m = self._momentum
            self._mean = m * self._mean + (1 - m) * jnp.asarray(
                v.mean(axis=0))
            self._var = m * self._var + (1 - m) * jnp.asarray(
                v.var(axis=0))
        return SparseCooTensor(x._indices, vals, x._shape)


class SyncBatchNorm(BatchNorm):
    """Single-controller SPMD: batch stats are already global (the
    values array spans the mesh), so Sync == BatchNorm."""


class _SparseConv(Layer):
    _nd = 3
    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        nd = self._nd
        ks = ((kernel_size,) * nd if isinstance(kernel_size, int)
              else tuple(kernel_size))
        self._ks = ks
        self._stride = ((stride,) * nd if isinstance(stride, int)
                        else tuple(stride))
        self._padding = ((padding,) * nd if isinstance(padding, int)
                         else tuple(padding))
        self._dilation = ((dilation,) * nd if isinstance(dilation, int)
                          else tuple(dilation))
        self._groups = int(groups)
        self.weight = self.create_parameter(
            list(ks) + [in_channels // groups, out_channels],
            attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        from . import SparseCooTensor, sparse_coo_from_dense
        if not isinstance(x, SparseCooTensor):
            raise TypeError("sparse conv expects a COO tensor")
        from ..ops.dispatch import apply_op
        nd = self._nd
        dense = x.to_dense()  # [N, *spatial, C]

        def fn(d, w, *rest):
            b = rest[0] if rest else None
            dn = jax.lax.conv_dimension_numbers(
                d.shape, w.shape,
                ("NDHWC", "DHWIO", "NDHWC") if nd == 3
                else ("NHWC", "HWIO", "NHWC"))
            out = jax.lax.conv_general_dilated(
                d, w, self._stride,
                [(p, p) for p in self._padding],
                rhs_dilation=self._dilation,
                feature_group_count=self._groups, dimension_numbers=dn)
            if b is not None:
                out = out + b
            return out

        args = (dense, self.weight) + (() if self.bias is None
                                       else (self.bias,))
        out = apply_op("sparse_conv", fn, args, {})
        if self._subm:
            # submanifold: only the input's active sites stay active
            if any(s != 1 for s in self._stride):
                raise ValueError("SubmConv requires stride 1")
            if tuple(out.shape[:-1]) != tuple(dense.shape[:-1]):
                raise ValueError(
                    f"SubmConv must preserve the spatial shape "
                    f"(got {tuple(out.shape[:-1])} from "
                    f"{tuple(dense.shape[:-1])}); use 'same' padding "
                    f"(padding = kernel//2)")
            idx = x._indices          # [1+nd, nnz] batch+spatial sites
            sp_idx = tuple(idx._data[i] for i in range(idx.shape[0]))
            gathered = out._data[sp_idx]    # [nnz, C_out]
            return SparseCooTensor(idx, Tensor(gathered),
                                   tuple(out.shape))
        # dense conv: emit the SAME site-indexed COO form SubmConv and
        # BatchNorm consume (indices over batch+spatial, values [nnz, C])
        arr = np.asarray(out._data)
        active = np.nonzero(np.abs(arr).sum(axis=-1) > 0)
        site_idx = np.stack(active)
        vals = arr[active]
        return SparseCooTensor(Tensor(jnp.asarray(site_idx)),
                               Tensor(jnp.asarray(vals)),
                               tuple(out.shape))


class Conv3D(_SparseConv):
    _nd = 3


class Conv2D(_SparseConv):
    _nd = 2


class SubmConv3D(_SparseConv):
    _nd = 3
    _subm = True


class SubmConv2D(_SparseConv):
    _nd = 2
    _subm = True


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding)

    def forward(self, x):
        from . import SparseCooTensor, sparse_coo_from_dense
        from ..nn import functional as F
        if not isinstance(x, SparseCooTensor):
            raise TypeError("sparse MaxPool3D expects a COO tensor")
        d = x.to_dense()  # [N, D, H, W, C]
        k, s, p = self._args
        out = F.max_pool3d(Tensor(jnp.transpose(d._data, (0, 4, 1, 2, 3))),
                           k, s, p)
        return sparse_coo_from_dense(
            Tensor(jnp.transpose(out._data, (0, 2, 3, 4, 1))))
