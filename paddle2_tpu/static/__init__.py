"""paddle.static (reference python/paddle/static/__init__.py).

TPU-native position: the reference's build-then-run Program/Executor stack
(SURVEY §2.2 static graph API) is subsumed by jit.to_static — one traced,
XLA-compiled program. This module keeps the static surface importable:
InputSpec and the inference-model save/load are fully functional (they map
onto the StableHLO export); Program/Executor shims run imperatively so
simple reference scripts keep working.
"""

from __future__ import annotations

import warnings
from typing import List, Optional

from ..jit.api import InputSpec  # full-featured (symbolic-dim export)
from ..framework.tensor import Tensor

__all__ = ["InputSpec", "Program", "default_main_program",
           "default_startup_program", "program_guard", "name_scope",
           "Executor", "global_scope", "save_inference_model",
           "load_inference_model", "data", "gradients", "py_func", "nn",
           "amp", "device_guard"]


class Program:
    """Shim: eager/jit execution has no separate program object; this
    records nothing and exists so reference-style code constructs."""

    def __init__(self):
        self.random_seed = 0

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return Program()


_main = Program()
_startup = Program()


def default_main_program() -> Program:
    return _main


def default_startup_program() -> Program:
    return _startup


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class name_scope:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class device_guard:
    def __init__(self, device=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> InputSpec:
    """static.data returns an InputSpec placeholder (eager feed model)."""
    return InputSpec(shape, dtype, name)


class Executor:
    """Shim executor: run() calls a python program eagerly. The reference's
    graph interpreter (SURVEY §1 L4) has no counterpart because jit
    compiles the whole step; this keeps run()-style scripts alive."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if callable(program):
            out = program(**(feed or {}))
            return out if isinstance(out, (list, tuple)) else [out]
        if fetch_list:
            return list(fetch_list)
        return []


def global_scope():
    return {}


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor
                         =None, program=None, **kwargs):
    """Maps onto jit.save: feed_vars carry the input specs; fetch_vars the
    layer whose forward produces them (reference static.io:save_inference_
    model contract, StableHLO artifact)."""
    from .. import jit
    layer = kwargs.get("layer")
    if layer is None and hasattr(fetch_vars, "parameters"):
        layer = fetch_vars
    if layer is None:
        raise ValueError(
            "TPU static shim: pass the Layer as fetch_vars (or layer=) — "
            "there is no global graph to cut feed/fetch out of")
    specs = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    jit.save(layer, path_prefix, input_spec=list(specs))


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    from .. import jit
    loaded = jit.load(path_prefix)
    return [loaded, [], []]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.tape import grad
    outs = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return list(grad(outs, ins, grad_outputs=target_gradients,
                     retain_graph=True, allow_unused=True))


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input
            =None):
    xs = x if isinstance(x, (list, tuple)) else [x]
    return func(*xs)


class nn:
    """static.nn namespace: the fc/conv helpers map to dygraph layers."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        raise NotImplementedError(
            "static.nn.fc: build models with paddle.nn.Linear — the static "
            "block builder has no TPU counterpart")


class amp:
    """static.amp namespace parity: decorate maps to paddle.amp."""

    @staticmethod
    def decorate(*args, **kwargs):
        from .. import amp as _amp
        return _amp.decorate(*args, **kwargs)
