"""paddle.static (reference python/paddle/static/__init__.py).

TPU-native position: the reference's build-then-run Program/Executor
stack (SURVEY §2.2 static graph API; ProgramDesc + the L4 graph
interpreter) maps onto RECORD-THEN-JIT: under ``program_guard`` every
dispatched op (all ops flow through ``ops.dispatch.apply_op``) is
recorded into the active :class:`Program` as a replayable node;
``Executor.run(program, feed, fetch_list)`` replays the recording as
ONE pure function of the feeds — compiled by XLA via ``jax.jit`` and
cached — reading parameter values LIVE at run time (so updates between
runs are visible, which is what the reference's scope-variable
semantics give). ``static.data`` placeholders are the feed points.

Scope (decision record): forward/inference programs. Static-graph
TRAINING (append_backward + optimizer ops inside the program) stays on
``jit.to_static`` / ``jit.train_step`` — on TPU the differentiated,
donated training step IS the compiled program, and rebuilding the
reference's op-level backward builder would duplicate it for no
benefit. ``static.gradients`` works OUTSIDE recording via the eager
tape.
"""

from __future__ import annotations

import threading
import warnings
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..jit.api import InputSpec  # full-featured (symbolic-dim export)
from ..framework.tensor import Tensor

__all__ = ["InputSpec", "Program", "default_main_program",
           "default_startup_program", "program_guard", "name_scope",
           "Executor", "global_scope", "save_inference_model",
           "load_inference_model", "data", "gradients", "py_func", "nn",
           "amp", "device_guard", "append_backward"]

_TLS = threading.local()


class Program:
    """Recorded op graph (reference Program/ProgramDesc analog).

    Nodes are (op_name, fn, kwargs, input_ids, output_ids) where fn is
    the SAME pure JAX function eager dispatch ran (autocast baked in at
    record time) — replay feeds new arrays through it, so one
    definition serves eager, jit, and static execution. Inputs that are
    not produced inside the program (parameters, captured constants)
    are read from the live Tensor at run() time. The Program holds
    strong references to every build-time tensor (id-keyed graph needs
    them alive): build with small placeholder shapes — run() shapes are
    pinned to the build shapes anyway.
    """

    def __init__(self):
        self.random_seed = 0
        self._nodes: List[tuple] = []
        self._feeds: Dict[str, Tensor] = {}
        self._live: Dict[int, Tensor] = {}   # id -> Tensor keepalive
        self._version = 0
        self._exec_cache: Dict[Any, Any] = {}

    # -- recording ------------------------------------------------------
    def _record(self, name, fn, kwargs, in_tensors, out):
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        in_ids = []
        for t in in_tensors:
            in_ids.append(id(t))
            self._live[id(t)] = t
        out_ids = []
        for t in outs:
            out_ids.append(id(t))
            self._live[id(t)] = t
        self._nodes.append((name, fn, dict(kwargs), tuple(in_ids),
                            tuple(out_ids)))
        self._version += 1

    def _add_feed(self, name: str, t: Tensor):
        self._feeds[name] = t
        self._live[id(t)] = t
        self._version += 1

    # -- execution ------------------------------------------------------
    def _execute(self, feed: Dict[str, Any], fetch_list) -> List:
        import numpy as np
        if not self._nodes:
            raise ValueError(
                "Program is empty — build it under "
                "`with static.program_guard(prog):` (ops dispatched "
                "there are recorded)")
        missing = [n for n in self._feeds if n not in feed]
        if missing:
            raise ValueError(f"run() missing feeds {missing}")
        for n, v in feed.items():
            ph = self._feeds.get(n)
            if ph is None:
                raise ValueError(
                    f"run() fed unknown placeholder {n!r}; program "
                    f"feeds are {sorted(self._feeds)}")
            got = tuple(getattr(v, "shape", np.shape(v)))
            want = tuple(ph.shape)
            if got != want:
                raise ValueError(
                    f"feed {n!r} shape {got} != built shape {want} — "
                    "recorded nodes bake build-time dims, so run() "
                    "shapes must match static.data's (build with the "
                    "real batch size; -1 dims become 1)")
        fetches = fetch_list if isinstance(fetch_list, (list, tuple)) \
            else [fetch_list]
        fetch_ids = tuple(id(t) for t in fetches)
        unknown = [i for i, t in zip(fetch_ids, fetches)
                   if i not in self._live]
        if unknown:
            raise ValueError(
                "fetch_list contains tensors the program did not "
                "produce")

        feed_arrays = {n: (v._data if isinstance(v, Tensor)
                           else jnp.asarray(v))
                       for n, v in feed.items()}
        # external inputs: ids consumed but never produced and not feeds
        produced = {i for node in self._nodes for i in node[4]}
        feed_ids = {id(t): n for n, t in self._feeds.items()}
        ext_ids = []
        for node in self._nodes:
            for i in node[3]:
                if i not in produced and i not in feed_ids \
                        and i not in ext_ids:
                    ext_ids.append(i)
        ext_arrays = [self._live[i]._data for i in ext_ids]

        key = (self._version, fetch_ids,
               tuple(sorted((n, tuple(a.shape), str(a.dtype))
                            for n, a in feed_arrays.items())))
        fn = self._exec_cache.get(key)
        if fn is None:
            nodes = list(self._nodes)
            feed_name_by_id = dict(feed_ids)
            ext_index = {i: k for k, i in enumerate(ext_ids)}

            def replay(feed_vals: Dict[str, Any], ext_vals):
                env: Dict[int, Any] = {}
                for i, n in feed_name_by_id.items():
                    env[i] = feed_vals[n]
                for i, k in ext_index.items():
                    env[i] = ext_vals[k]

                def val(i):
                    if i in env:
                        return env[i]
                    return self._live[i]._data   # baked const (rare)

                for name, f, kw, in_ids, out_ids in nodes:
                    args = [val(i) for i in in_ids]
                    out = f(*args, **kw) if kw else f(*args)
                    outs = list(out) if isinstance(out, (tuple, list)) \
                        else [out]
                    for i, o in zip(out_ids, outs):
                        env[i] = o
                return [env[i] for i in fetch_ids]

            fn = jax.jit(replay)
            if len(self._exec_cache) > 64:
                self._exec_cache.clear()
            self._exec_cache[key] = fn
        outs = fn(feed_arrays, ext_arrays)
        return [np.asarray(o) for o in outs]

    # -- reference surface ---------------------------------------------
    def global_block(self):
        return self

    def clone(self, for_test=False):
        p = Program()
        p._nodes = list(self._nodes)
        p._feeds = dict(self._feeds)
        p._live = dict(self._live)
        p._version = self._version
        return p


_main = Program()
_startup = Program()


def default_main_program() -> Program:
    return _main


def default_startup_program() -> Program:
    return _startup


def _active_program() -> Optional[Program]:
    return getattr(_TLS, "program", None)


_GUARD_LOCK = threading.Lock()
_GUARD_COUNT = 0


def _recorder(name, fn, kw, ins, out):
    prog = getattr(_TLS, "program", None)
    if prog is not None:
        prog._record(name, fn, kw, ins, out)


class program_guard:
    """Route op recording into `main_program` (reference
    static.program_guard build-then-run contract). The dispatch hook is
    installed while ANY thread has an open guard (refcounted) and reads
    the thread-local program, so concurrent guards on different threads
    record independently."""

    def __init__(self, main_program=None, startup_program=None):
        self.program = main_program if main_program is not None else _main

    def __enter__(self):
        global _GUARD_COUNT
        from ..ops import dispatch
        self._prev = getattr(_TLS, "program", None)
        _TLS.program = self.program
        if self._prev is None:      # outermost guard on this thread
            with _GUARD_LOCK:
                _GUARD_COUNT += 1
                dispatch.set_static_recorder(_recorder)
        return self

    def __exit__(self, *exc):
        global _GUARD_COUNT
        from ..ops import dispatch
        _TLS.program = self._prev
        if self._prev is None:
            with _GUARD_LOCK:
                _GUARD_COUNT -= 1
                if _GUARD_COUNT == 0:
                    dispatch.set_static_recorder(None)
        return False


class name_scope:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class device_guard:
    def __init__(self, device=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def data(name: str, shape, dtype="float32", lod_level=0):
    """Feed placeholder. Under an active ``program_guard`` this is a
    real placeholder Tensor registered as the program's feed point
    (-1 dims become 1 for build-time shapes; run() feeds must match the
    built shapes). Outside a guard it stays an InputSpec for the
    jit.save export path."""
    prog = _active_program()
    if prog is None:
        return InputSpec(shape, dtype, name)
    concrete = tuple(1 if (d is None or int(d) < 0) else int(d)
                     for d in shape)
    t = Tensor(jnp.zeros(concrete, dtype), stop_gradient=True)
    prog._add_feed(name, t)
    return t


class Executor:
    """Executor.run replays a recorded Program as one jitted function
    of the feeds (reference's L4 graph interpreter, re-expressed as XLA
    compile-and-cache). Callables still run directly, so both styles of
    reference script work."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if callable(program) and not isinstance(program, Program):
            out = program(**(feed or {}))
            return out if isinstance(out, (list, tuple)) else [out]
        prog = program if isinstance(program, Program) else _main
        if prog._nodes or prog._feeds:
            return prog._execute(feed or {}, fetch_list or [])
        if fetch_list:
            return list(fetch_list)
        return []


def global_scope():
    return {}


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor
                         =None, program=None, **kwargs):
    """Maps onto jit.save: feed_vars carry the input specs; fetch_vars the
    layer whose forward produces them (reference static.io:save_inference_
    model contract, StableHLO artifact)."""
    from .. import jit
    layer = kwargs.get("layer")
    if layer is None and hasattr(fetch_vars, "parameters"):
        layer = fetch_vars
    if layer is None:
        raise ValueError(
            "TPU static shim: pass the Layer as fetch_vars (or layer=) — "
            "there is no global graph to cut feed/fetch out of")
    specs = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    jit.save(layer, path_prefix, input_spec=list(specs))


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    from .. import jit
    loaded = jit.load(path_prefix)
    return [loaded, [], []]


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Reference static.append_backward builds op-level backward into
    the Program. Decision record (module docstring): static-graph
    TRAINING maps onto ``jit.train_step`` / ``jit.to_static`` — the
    differentiated, donated training step IS the compiled program on
    TPU. Use those, or ``static.gradients`` on eager tensors."""
    raise NotImplementedError(
        "paddle.static.append_backward: static-graph training maps onto "
        "jit.train_step / jit.to_static on this framework (see "
        "paddle2_tpu/static/__init__.py decision record); "
        "static.gradients works on eager tensors")


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.tape import grad
    outs = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return list(grad(outs, ins, grad_outputs=target_gradients,
                     retain_graph=True, allow_unused=True))


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input
            =None):
    xs = x if isinstance(x, (list, tuple)) else [x]
    return func(*xs)


class nn:
    """static.nn namespace (reference python/paddle/static/nn/): the
    block builders create parameters and dispatch the SAME ops eager
    dispatch runs — under ``program_guard`` the recorder captures them,
    so build-then-run works like the reference's layer helpers.
    Sequence (LoD) ops raise: LoD tensors are replaced by ragged/packed
    batches in this framework (see flash_attn_unpadded / varlen)."""

    _name_counter = {}

    @staticmethod
    def _uname(base):
        """Unique parameter names per builder call (the reference's
        unique_name.generate) so name-based matching never collides."""
        k = nn._name_counter.get(base, 0)
        nn._name_counter[base] = k + 1
        return f"{base}_{k}" if k else base

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        from ..ops.dispatch import ensure_tensor
        t = ensure_tensor(x)
        import numpy as _np
        in_f = int(_np.prod(t.shape[num_flatten_dims:]))
        w = create_parameter([in_f, size], "float32",
                             name=nn._uname(f"{name or 'fc'}_w"))
        b = create_parameter([size], "float32", is_bias=True,
                             name=nn._uname(f"{name or 'fc'}_b"))
        from ..nn import functional as F
        flat = t.reshape(list(t.shape[:num_flatten_dims]) + [in_f])
        out = F.linear(flat, w, b)
        if activation:
            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def embedding(input, size, is_sparse=False, padding_idx=None,
                  param_attr=None, dtype="float32"):
        w = create_parameter(list(size), dtype, name=nn._uname("embedding_w"))
        from ..nn import functional as F
        return F.embedding(input, w, padding_idx=padding_idx)

    @staticmethod
    def sparse_embedding(input, size, padding_idx=None, param_attr=None,
                         dtype="float32", **kwargs):
        """PS-era sparse table embedding: the TPU path is the sharded
        dense table (distributed.ps.SparseTable decision record)."""
        return nn.embedding(input, size, padding_idx=padding_idx,
                            dtype=dtype)

    @staticmethod
    def _conv(x, num_filters, filter_size, nd, stride=1, padding=0,
              dilation=1, groups=1, act=None, transpose=False,
              name="conv", output_size=None):
        from ..ops.dispatch import ensure_tensor
        t = ensure_tensor(x)
        cin = int(t.shape[1])
        if filter_size is None:
            if not (transpose and output_size is not None):
                raise ValueError(
                    f"{name}: filter_size is required (output_size can "
                    "derive it only for transpose convs)")
            # k = out - (in - 1) * stride + 2 * pad (reference derivation)
            outs = ([output_size] * nd if isinstance(output_size, int)
                    else list(output_size))[-nd:]
            st_ = ([stride] * nd if isinstance(stride, int)
                   else list(stride))
            pd = ([padding] * nd if isinstance(padding, int)
                  else list(padding))
            filter_size = [int(outs[i] - (int(t.shape[2 + i]) - 1)
                               * st_[i] + 2 * pd[i]) for i in range(nd)]
        ks = ([filter_size] * nd if isinstance(filter_size, int)
              else list(filter_size))
        from ..nn import functional as F
        if transpose:
            w = create_parameter([cin, num_filters // groups] + ks,
                                 "float32", name=nn._uname(f"{name}_w"))
            fn = F.conv2d_transpose if nd == 2 else F.conv3d_transpose
        else:
            w = create_parameter([num_filters, cin // groups] + ks,
                                 "float32", name=nn._uname(f"{name}_w"))
            fn = F.conv2d if nd == 2 else F.conv3d
        b = create_parameter([num_filters], "float32", is_bias=True,
                             name=nn._uname(f"{name}_b"))
        out = fn(t, w, b, stride=stride, padding=padding,
                 dilation=dilation, groups=groups)
        if act:
            out = getattr(F, act)(out)
        return out

    @staticmethod
    def conv2d(input, num_filters, filter_size, stride=1, padding=0,
               dilation=1, groups=1, param_attr=None, bias_attr=None,
               act=None, name=None, **kw):
        return nn._conv(input, num_filters, filter_size, 2, stride,
                        padding, dilation, groups, act, name=name or
                        "conv2d")

    @staticmethod
    def conv3d(input, num_filters, filter_size, stride=1, padding=0,
               dilation=1, groups=1, param_attr=None, bias_attr=None,
               act=None, name=None, **kw):
        return nn._conv(input, num_filters, filter_size, 3, stride,
                        padding, dilation, groups, act, name=name or
                        "conv3d")

    @staticmethod
    def conv2d_transpose(input, num_filters, filter_size=None,
                         output_size=None, stride=1, padding=0,
                         dilation=1, groups=1, param_attr=None,
                         bias_attr=None, act=None, name=None, **kw):
        return nn._conv(input, num_filters, filter_size, 2, stride,
                        padding, dilation, groups, act, transpose=True,
                        name=name or "conv2d_transpose",
                        output_size=output_size)

    @staticmethod
    def conv3d_transpose(input, num_filters, filter_size=None,
                         output_size=None, stride=1, padding=0,
                         dilation=1, groups=1, param_attr=None,
                         bias_attr=None, act=None, name=None, **kw):
        return nn._conv(input, num_filters, filter_size, 3, stride,
                        padding, dilation, groups, act, transpose=True,
                        name=name or "conv3d_transpose",
                        output_size=output_size)

    @staticmethod
    def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
                   param_attr=None, bias_attr=None, data_layout="NCHW",
                   **kw):
        from ..ops.dispatch import ensure_tensor
        t = ensure_tensor(input)
        c = int(t.shape[1])
        import jax.numpy as _j
        scale = create_parameter([c], "float32", name=nn._uname("bn_scale"))
        scale._replace_data(_j.ones([c], _j.float32))
        bias = create_parameter([c], "float32", is_bias=True,
                                name=nn._uname("bn_bias"))
        mean = create_global_var([c], 0.0, "float32", name=nn._uname("bn_mean"))
        var = create_global_var([c], 1.0, "float32", name=nn._uname("bn_var"))
        from ..nn import functional as F
        out = F.batch_norm(t, mean, var, weight=scale, bias=bias,
                           training=True, momentum=momentum,
                           epsilon=epsilon)
        if act:
            out = getattr(F, act)(out)
        return out

    @staticmethod
    def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
                   epsilon=1e-5, param_attr=None, bias_attr=None,
                   act=None):
        from ..ops.dispatch import ensure_tensor
        import numpy as _np
        t = ensure_tensor(input)
        shape = [int(s) for s in t.shape[begin_norm_axis:]]
        import jax.numpy as _j
        w = create_parameter(shape, "float32", name=nn._uname("ln_scale"))
        w._replace_data(_j.ones(shape, _j.float32))
        b = create_parameter(shape, "float32", is_bias=True,
                             name=nn._uname("ln_bias"))
        from ..nn import functional as F
        out = F.layer_norm(t, t.shape[begin_norm_axis:],
                           weight=w if scale else None,
                           bias=b if shift else None, epsilon=epsilon)
        if act:
            out = getattr(F, act)(out)
        return out

    @staticmethod
    def group_norm(input, groups, epsilon=1e-5, param_attr=None,
                   bias_attr=None, act=None, data_layout="NCHW"):
        from ..ops.dispatch import ensure_tensor
        t = ensure_tensor(input)
        c = int(t.shape[1])
        import jax.numpy as _j
        w = create_parameter([c], "float32", name=nn._uname("gn_scale"))
        w._replace_data(_j.ones([c], _j.float32))
        b = create_parameter([c], "float32", is_bias=True,
                             name=nn._uname("gn_bias"))
        from ..nn import functional as F
        out = F.group_norm(t, groups, epsilon=epsilon, weight=w, bias=b)
        if act:
            out = getattr(F, act)(out)
        return out

    @staticmethod
    def instance_norm(input, epsilon=1e-5, param_attr=None,
                      bias_attr=None):
        from ..ops.dispatch import ensure_tensor
        t = ensure_tensor(input)
        c = int(t.shape[1])
        import jax.numpy as _j
        w = create_parameter([c], "float32", name=nn._uname("in_scale"))
        w._replace_data(_j.ones([c], _j.float32))
        b = create_parameter([c], "float32", is_bias=True,
                             name=nn._uname("in_bias"))
        from ..nn import functional as F
        return F.instance_norm(t, weight=w, bias=b, eps=epsilon)

    @staticmethod
    def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
                  **kw):
        """data_norm: normalization by accumulated batch statistics —
        the stateless equivalent normalizes by the CURRENT batch."""
        from ..ops.dispatch import ensure_tensor, apply_op
        import jax.numpy as _j
        t = ensure_tensor(input)

        def fn(a):
            mu = _j.mean(a, axis=0, keepdims=True)
            var = _j.var(a, axis=0, keepdims=True)
            return (a - mu) / _j.sqrt(var + epsilon)
        out = apply_op("data_norm", fn, (t,), {})
        if act:
            from ..nn import functional as F
            out = getattr(F, act)(out)
        return out

    @staticmethod
    def prelu(x, mode="all", param_attr=None, data_format="NCHW",
              name=None):
        from ..ops.dispatch import ensure_tensor
        t = ensure_tensor(x)
        n = (1 if mode == "all" else int(t.shape[1]))
        import jax.numpy as _j
        alpha = create_parameter([n], "float32", name=nn._uname("prelu_alpha"))
        alpha._replace_data(_j.full([n], 0.25, _j.float32))
        from ..nn import functional as F
        return F.prelu(t, alpha)

    @staticmethod
    def deform_conv2d(x, offset, mask, num_filters, filter_size,
                      stride=1, padding=0, dilation=1, groups=1,
                      deformable_groups=1, im2col_step=1,
                      param_attr=None, bias_attr=None, name=None):
        from ..ops.dispatch import ensure_tensor
        from ..vision.ops import deform_conv2d as _dc
        t = ensure_tensor(x)
        cin = int(t.shape[1])
        ks = ([filter_size] * 2 if isinstance(filter_size, int)
              else list(filter_size))
        w = create_parameter([num_filters, cin // groups] + ks,
                             "float32", name=nn._uname("deform_w"))
        b = create_parameter([num_filters], "float32", is_bias=True,
                             name=nn._uname("deform_b"))
        return _dc(t, offset, w, b, stride=stride, padding=padding,
                   dilation=dilation, deformable_groups=deformable_groups,
                   groups=groups, mask=mask)

    @staticmethod
    def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                                bias_attr=None, name=None):
        from ..ops.dispatch import apply_op, ensure_tensor
        import jax.numpy as _j
        xt, yt = ensure_tensor(x), ensure_tensor(y)
        dx, dy = int(xt.shape[-1]), int(yt.shape[-1])
        w = create_parameter([size, dx, dy], "float32", name=nn._uname("btp_w"))
        b = create_parameter([size], "float32", is_bias=True,
                             name=nn._uname("btp_b"))

        def fn(a, c, wv, bv):
            return _j.einsum("bi,kij,bj->bk", a, wv, c) + bv
        out = apply_op("bilinear_tensor_product", fn, (xt, yt, w, b), {})
        if act:
            from ..nn import functional as F
            out = getattr(F, act)(out)
        return out

    @staticmethod
    def nce(input, label, num_total_classes, sample_weight=None,
            param_attr=None, bias_attr=None, num_neg_samples=None,
            name=None, sampler="uniform", custom_dist=None, seed=0,
            is_sparse=False):
        """Noise-contrastive estimation loss over a learned class
        matrix (static/nn/common.py nce): log-sigmoid positive + k
        uniform negatives."""
        from ..ops.dispatch import apply_op, ensure_tensor
        import jax
        import jax.numpy as _j
        from ..framework import random as fr
        xt = ensure_tensor(input)
        lt = ensure_tensor(label)
        d = int(xt.shape[-1])
        k = num_neg_samples or 10
        w = create_parameter([num_total_classes, d], "float32",
                             name=nn._uname("nce_w"))
        b = create_parameter([num_total_classes], "float32",
                             is_bias=True, name=nn._uname("nce_b"))
        key = fr.next_key()

        def fn(a, y, wv, bv):
            y = y.reshape(-1).astype(_j.int32)
            pos = _j.einsum("bd,bd->b", a, wv[y]) + bv[y]
            neg_ids = jax.random.randint(key, (a.shape[0], k), 0,
                                         num_total_classes)
            neg = _j.einsum("bd,bkd->bk", a, wv[neg_ids]) + bv[neg_ids]
            loss = (-jax.nn.log_sigmoid(pos)
                    - _j.sum(jax.nn.log_sigmoid(-neg), axis=1))
            return loss[:, None]
        return apply_op("nce", fn, (xt, lt, w, b), {})

    @staticmethod
    def row_conv(input, future_context_size, param_attr=None, act=None):
        """row_conv (lookahead conv, static/nn/common.py): each step t
        mixes steps t..t+k with a per-feature learned window."""
        from ..ops.dispatch import apply_op, ensure_tensor
        import jax.numpy as _j
        t = ensure_tensor(input)              # [B, T, D]
        d = int(t.shape[-1])
        k = future_context_size + 1
        w = create_parameter([k, d], "float32", name=nn._uname("row_conv_w"))

        def fn(a, wv):
            outs = 0.0
            for i in range(k):
                shifted = _j.concatenate(
                    [a[:, i:], _j.zeros_like(a[:, :i])], axis=1)
                outs = outs + shifted * wv[i]
            return outs
        out = apply_op("row_conv", fn, (t, w), {})
        if act:
            from ..nn import functional as F
            out = getattr(F, act)(out)
        return out

    @staticmethod
    def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12,
                      name=None):
        from ..ops.dispatch import apply_op, ensure_tensor
        import jax.numpy as _j
        import numpy as _np
        w = ensure_tensor(weight)

        def fn(wv):
            m = _j.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
            u = _j.asarray(_np.random.RandomState(0)
                           .randn(m.shape[0]).astype(_np.float32))
            u = u / _j.linalg.norm(u)
            v = m.T @ u
            v = v / _j.maximum(_j.linalg.norm(v), eps)
            for _ in range(power_iters):
                u = m @ v
                u = u / _j.maximum(_j.linalg.norm(u), eps)
                v = m.T @ u
                v = v / _j.maximum(_j.linalg.norm(v), eps)
            sigma = u @ (m @ v)
            return wv / sigma
        return apply_op("static_spectral_norm", fn, (w,), {})

    # -- control flow (host-evaluated: the recorded program replays the
    #    branch taken at BUILD time; data-dependent control flow at run
    #    time is jit.to_static's graph-break territory) --
    @staticmethod
    def cond(pred, true_fn=None, false_fn=None, name=None,
             return_names=None):
        from ..ops.dispatch import ensure_tensor
        import numpy as _np
        p = bool(_np.asarray(ensure_tensor(pred).numpy()).reshape(-1)[0])
        if p:
            return true_fn() if true_fn is not None else None
        return false_fn() if false_fn is not None else None

    @staticmethod
    def case(pred_fn_pairs, default=None, name=None):
        from ..ops.dispatch import ensure_tensor
        import numpy as _np
        for pred, fn in pred_fn_pairs:
            if bool(_np.asarray(ensure_tensor(pred).numpy())
                    .reshape(-1)[0]):
                return fn()
        return default() if default is not None else None

    @staticmethod
    def switch_case(branch_index, branch_fns, default=None, name=None):
        from ..ops.dispatch import ensure_tensor
        import numpy as _np
        idx = int(_np.asarray(ensure_tensor(branch_index).numpy())
                  .reshape(-1)[0])
        fns = dict(branch_fns) if not isinstance(branch_fns, dict)             else branch_fns
        if idx in fns:
            return fns[idx]()
        return default() if default is not None else None

    @staticmethod
    def while_loop(cond, body, loop_vars, is_test=False, name=None):
        from ..ops.dispatch import ensure_tensor
        import numpy as _np
        vars_ = list(loop_vars)
        while bool(_np.asarray(ensure_tensor(cond(*vars_)).numpy())
                   .reshape(-1)[0]):
            out = body(*vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
        return vars_

    @staticmethod
    def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
        """static_pylayer -> the dygraph PyLayer covers custom-grad
        blocks; inputs run through forward_fn directly."""
        return forward_fn(*inputs)

    @staticmethod
    def py_func(func, x, out, backward_func=None,
                skip_vars_in_backward_input=None):
        return py_func(func, x, out, backward_func,
                       skip_vars_in_backward_input)

    # -- LoD sequence ops: no LoD tensors on this stack --
    @staticmethod
    def _no_lod(op):
        raise NotImplementedError(
            f"static.nn.{op}: LoD (level-of-detail) sequence tensors are "
            "replaced by padded/packed ragged batches here — use "
            "nn.functional flash_attn_unpadded / pack by cu_seqlens "
            "(decision: ragged varlen path, README)")

    @staticmethod
    def sequence_conv(*a, **k):
        nn._no_lod("sequence_conv")

    @staticmethod
    def sequence_pool(*a, **k):
        nn._no_lod("sequence_pool")

    @staticmethod
    def sequence_softmax(*a, **k):
        nn._no_lod("sequence_softmax")

    @staticmethod
    def sequence_expand(*a, **k):
        nn._no_lod("sequence_expand")

    @staticmethod
    def sequence_first_step(*a, **k):
        nn._no_lod("sequence_first_step")

    @staticmethod
    def sequence_last_step(*a, **k):
        nn._no_lod("sequence_last_step")


class amp:
    """static.amp namespace parity: decorate maps to paddle.amp."""

    @staticmethod
    def decorate(*args, **kwargs):
        from .. import amp as _amp
        return _amp.decorate(*args, **kwargs)


# ---------------------------------------------------------------- r5
# remaining reference static surface (python/paddle/static/__init__.py):
# places, variables, program serialization, EMA, metric ops, IPU guards.

Variable = None  # forward decl, assigned below


class _Variable:
    """static.Variable: in this framework a static 'variable' IS an
    eager Tensor recorded into the active Program, so the class exists
    for isinstance checks and factory helpers."""

    def __new__(cls, *a, **k):
        raise TypeError("Variable is created via static.data/"
                        "create_parameter/create_global_var, not "
                        "directly")


Variable = _Variable


def cpu_places(device_count=None):
    """static cpu_places: the PJRT host platform devices."""
    import jax
    devs = [d for d in jax.devices() if d.platform == "cpu"]
    if not devs:
        devs = jax.devices()
    n = device_count or len(devs)
    return devs[:n]


def cuda_places(device_ids=None):
    """static cuda_places -> the accelerator devices (TPU here)."""
    import jax
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if not devs:
        devs = jax.devices()
    if device_ids is not None:
        devs = [devs[i] for i in device_ids]
    return devs


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """static.nn create_parameter: a live trainable Tensor."""
    from ..framework.tensor import Parameter
    import jax.numpy as jnp
    import numpy as np
    if is_bias and default_initializer is None:
        data = jnp.zeros(tuple(shape), dtype)
    else:
        import jax
        from ..framework import random as fr
        fan_in = int(np.prod(shape[:-1])) or 1
        bound = float(np.sqrt(6.0 / fan_in))
        data = jax.random.uniform(fr.next_key(), tuple(shape),
                                  jnp.float32, -bound, bound).astype(dtype)
    p = Parameter(data)
    p.name = name or f"create_parameter_{id(p)}"
    p.stop_gradient = False
    if default_initializer is not None:
        # nn.initializer protocol: initializer(param) fills in place
        default_initializer(p)
    return p


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    import jax.numpy as jnp
    from ..framework.tensor import Tensor
    t = Tensor(jnp.full(tuple(shape), value, dtype))
    t.persistable = persistable
    t.name = name or f"global_var_{id(t)}"
    return t


class scope_guard:
    """static.scope_guard: scopes are the live Python process here; the
    guard keeps reference code structure valid."""

    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self.scope

    def __exit__(self, *exc):
        return False


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """static normalize_program: prune to the feed->fetch slice. The
    recorded Program replays only reachable nodes at run time already;
    returns the program with feeds/fetches pinned."""
    program._feeds = list(feed_vars)
    program._fetches = list(fetch_vars)
    return program


def serialize_program(feed_vars, fetch_vars, **kwargs):
    import pickle
    return pickle.dumps({"feeds": [getattr(v, "name", None)
                                   for v in feed_vars],
                         "fetches": [getattr(v, "name", None)
                                     for v in fetch_vars]})


def deserialize_program(data):
    import pickle
    meta = pickle.loads(data)
    p = Program()
    p._meta = meta
    return p


def serialize_persistables(feed_vars, fetch_vars, executor=None):
    import pickle
    import numpy as np
    state = {}
    for v in list(feed_vars) + list(fetch_vars):
        if hasattr(v, "_data") and getattr(v, "persistable", False):
            state[getattr(v, "name", str(id(v)))] = np.asarray(v._data)
    return pickle.dumps(state)


def deserialize_persistables(program, data, executor=None):
    import pickle
    return pickle.loads(data)


def save_to_file(path, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def save(program, model_path, protocol=4, **configs):
    """static.save: every trainable Tensor the Program keeps alive."""
    from ..framework import io_state
    state = {}
    for t in getattr(program, "_live", {}).values():
        if getattr(t, "stop_gradient", True) is False:
            state[getattr(t, "name", str(id(t)))] = t
    io_state.save(state, model_path + ".pdparams")


def load(program, model_path, executor=None, var_list=None):
    from ..framework import io_state
    state = io_state.load(model_path + ".pdparams")
    by_name = {getattr(t, "name", None): t
               for t in getattr(program, "_live", {}).values()}
    import jax.numpy as jnp
    for k, v in state.items():
        if k in by_name and by_name[k] is not None:
            arr = v._data if hasattr(v, "_data") else jnp.asarray(v)
            by_name[k]._replace_data(arr)
    return state


def load_program_state(model_path, var_list=None):
    from ..framework import io_state
    import numpy as np
    state = io_state.load(model_path + ".pdparams")
    return {k: np.asarray(v._data if hasattr(v, "_data") else v)
            for k, v in state.items()}


def set_program_state(program, state):
    import jax.numpy as jnp
    by_name = {getattr(t, "name", None): t
               for t in getattr(program, "_live", {}).values()}
    for k, v in state.items():
        if k in by_name and by_name[k] is not None:
            by_name[k]._replace_data(jnp.asarray(v))


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """static.Print: debug-print a tensor as it flows (jax.debug.print
    under jit; direct print eagerly)."""
    import numpy as np
    from ..ops.dispatch import ensure_tensor
    t = ensure_tensor(input)
    head = message or (getattr(t, "name", "var")
                       if print_tensor_name else "")
    arr = np.asarray(t.numpy()).ravel()[:summarize]
    print(f"{head} shape={list(t.shape)} dtype={t.dtype}: {arr}")
    return input


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """static.accuracy op: top-k accuracy over softmax scores."""
    import jax.numpy as jnp
    from ..ops.dispatch import apply_op, ensure_tensor

    def fn(x, y):
        topk = jnp.argsort(-x, axis=-1)[:, :k]
        hit = (topk == y.reshape(-1, 1)).any(axis=1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply_op("accuracy", fn,
                    (ensure_tensor(input), ensure_tensor(label)), {},
                    differentiable=False)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """static.auc op: ROC-AUC of positive-class scores (threshold-bucket
    approximation like the reference kernel)."""
    import jax.numpy as jnp
    from ..ops.dispatch import apply_op, ensure_tensor

    def fn(x, y):
        pos_score = x[:, 1] if x.ndim == 2 and x.shape[1] > 1 else \
            x.reshape(-1)
        yb = y.reshape(-1).astype(jnp.float32)
        edges = jnp.linspace(0.0, 1.0, num_thresholds + 1)
        idx = jnp.clip(jnp.searchsorted(edges, pos_score) - 1, 0,
                       num_thresholds - 1)
        pos_hist = jax.ops.segment_sum(yb, idx, num_thresholds)
        neg_hist = jax.ops.segment_sum(1.0 - yb, idx, num_thresholds)
        # integrate from the high-score end
        tp = jnp.cumsum(pos_hist[::-1])
        fp = jnp.cumsum(neg_hist[::-1])
        tot_pos = jnp.maximum(tp[-1], 1e-9)
        tot_neg = jnp.maximum(fp[-1], 1e-9)
        tpr = jnp.concatenate([jnp.zeros(1), tp / tot_pos])
        fpr = jnp.concatenate([jnp.zeros(1), fp / tot_neg])
        return jnp.trapezoid(tpr, fpr)

    import jax
    res = apply_op("auc", fn,
                   (ensure_tensor(input), ensure_tensor(label)), {},
                   differentiable=False)
    return res, [res], [res]


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """static ctr_metric_bundle: (auc, batch_auc, ...) for CTR models."""
    a, _, _ = auc(input, label)
    return a, a


class ExponentialMovingAverage:
    """static.ExponentialMovingAverage: shadow EMA weights with
    apply()/restore() swap, thres_steps-style bias correction."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._params = None
        self._shadow = {}
        self._saved = None
        self._step = 0

    def _ensure(self):
        if self._params is None:
            raise RuntimeError(
                "call update() after registering parameters via "
                "update(parameters=...) once")

    def update(self, parameters=None):
        import jax.numpy as jnp
        if parameters is not None:
            self._params = [p for p in parameters if p is not None]
        self._ensure()
        self._step += 1
        d = self._decay
        if self._thres_steps is not None:
            # reference: decay ramps by global step only when thres_steps
            # is supplied (ExponentialMovingAverage thres_steps docs)
            d = min(d, (1.0 + self._step) / (10.0 + self._step))
        for p in self._params:
            key = id(p)
            prev = self._shadow.get(key)
            cur = p._data.astype(jnp.float32)
            self._shadow[key] = (cur if prev is None
                                 else d * prev + (1 - d) * cur)

    def apply(self, executor=None, need_restore=True):
        self._ensure()
        self._saved = ({id(p): p._data for p in self._params}
                       if need_restore else None)
        for p in self._params:
            sh = self._shadow.get(id(p))
            if sh is not None:
                p._replace_data(sh.astype(p._data.dtype))

    def restore(self, executor=None):
        if self._saved is None:
            return
        for p in self._params:
            p._replace_data(self._saved[id(p)])
        self._saved = None


class BuildStrategy:
    """Reference BuildStrategy knobs: XLA owns fusion/scheduling here;
    the attributes are accepted and recorded (inert by design)."""

    def __init__(self):
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.enable_auto_fusion = False
        self.memory_optimize = None
        self.reduce_strategy = None
        self.build_cinn_pass = False


class CompiledProgram:
    """Reference CompiledProgram(program, build_strategy): compilation
    happens at Executor.run (jit cache); wrapper keeps the API."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, name):
        return getattr(self._program, name)


class IpuStrategy:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "IPU is a different accelerator vertical; this framework "
            "targets TPU via XLA/PJRT (set_device('tpu')). There is no "
            "IPU lowering to configure.")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "IPU compilation has no TPU analog; use Executor.run (XLA "
            "jit cache) directly.")


class ipu_shard_guard:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "ipu_shard_guard has no TPU analog; shard with "
            "paddle.distributed shardings instead.")


class WeightNormParamAttr:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "WeightNormParamAttr: use paddle.nn.utils weight_norm-style "
            "parametrization on layers (deprecated in the reference).")


__all__ += ["Variable", "cpu_places", "cuda_places", "create_parameter",
            "create_global_var", "scope_guard", "normalize_program",
            "serialize_program", "deserialize_program",
            "serialize_persistables", "deserialize_persistables",
            "save_to_file", "load_from_file", "save", "load",
            "load_program_state", "set_program_state", "Print",
            "accuracy", "auc", "ctr_metric_bundle",
            "ExponentialMovingAverage", "BuildStrategy",
            "CompiledProgram", "IpuStrategy", "IpuCompiledProgram",
            "ipu_shard_guard", "WeightNormParamAttr"]


def xpu_places(device_ids=None):
    """static xpu_places: XPU is another vendor's accelerator; the
    accelerator devices here are TPUs (same role in scripts)."""
    return cuda_places(device_ids)


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise NotImplementedError(
        "set_ipu_shard has no TPU analog; use distributed shardings.")


__all__ += ["xpu_places", "set_ipu_shard"]
