"""paddle.static (reference python/paddle/static/__init__.py).

TPU-native position: the reference's build-then-run Program/Executor
stack (SURVEY §2.2 static graph API; ProgramDesc + the L4 graph
interpreter) maps onto RECORD-THEN-JIT: under ``program_guard`` every
dispatched op (all ops flow through ``ops.dispatch.apply_op``) is
recorded into the active :class:`Program` as a replayable node;
``Executor.run(program, feed, fetch_list)`` replays the recording as
ONE pure function of the feeds — compiled by XLA via ``jax.jit`` and
cached — reading parameter values LIVE at run time (so updates between
runs are visible, which is what the reference's scope-variable
semantics give). ``static.data`` placeholders are the feed points.

Scope (decision record): forward/inference programs. Static-graph
TRAINING (append_backward + optimizer ops inside the program) stays on
``jit.to_static`` / ``jit.train_step`` — on TPU the differentiated,
donated training step IS the compiled program, and rebuilding the
reference's op-level backward builder would duplicate it for no
benefit. ``static.gradients`` works OUTSIDE recording via the eager
tape.
"""

from __future__ import annotations

import threading
import warnings
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..jit.api import InputSpec  # full-featured (symbolic-dim export)
from ..framework.tensor import Tensor

__all__ = ["InputSpec", "Program", "default_main_program",
           "default_startup_program", "program_guard", "name_scope",
           "Executor", "global_scope", "save_inference_model",
           "load_inference_model", "data", "gradients", "py_func", "nn",
           "amp", "device_guard", "append_backward"]

_TLS = threading.local()


class Program:
    """Recorded op graph (reference Program/ProgramDesc analog).

    Nodes are (op_name, fn, kwargs, input_ids, output_ids) where fn is
    the SAME pure JAX function eager dispatch ran (autocast baked in at
    record time) — replay feeds new arrays through it, so one
    definition serves eager, jit, and static execution. Inputs that are
    not produced inside the program (parameters, captured constants)
    are read from the live Tensor at run() time. The Program holds
    strong references to every build-time tensor (id-keyed graph needs
    them alive): build with small placeholder shapes — run() shapes are
    pinned to the build shapes anyway.
    """

    def __init__(self):
        self.random_seed = 0
        self._nodes: List[tuple] = []
        self._feeds: Dict[str, Tensor] = {}
        self._live: Dict[int, Tensor] = {}   # id -> Tensor keepalive
        self._version = 0
        self._exec_cache: Dict[Any, Any] = {}

    # -- recording ------------------------------------------------------
    def _record(self, name, fn, kwargs, in_tensors, out):
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        in_ids = []
        for t in in_tensors:
            in_ids.append(id(t))
            self._live[id(t)] = t
        out_ids = []
        for t in outs:
            out_ids.append(id(t))
            self._live[id(t)] = t
        self._nodes.append((name, fn, dict(kwargs), tuple(in_ids),
                            tuple(out_ids)))
        self._version += 1

    def _add_feed(self, name: str, t: Tensor):
        self._feeds[name] = t
        self._live[id(t)] = t
        self._version += 1

    # -- execution ------------------------------------------------------
    def _execute(self, feed: Dict[str, Any], fetch_list) -> List:
        import numpy as np
        if not self._nodes:
            raise ValueError(
                "Program is empty — build it under "
                "`with static.program_guard(prog):` (ops dispatched "
                "there are recorded)")
        missing = [n for n in self._feeds if n not in feed]
        if missing:
            raise ValueError(f"run() missing feeds {missing}")
        for n, v in feed.items():
            ph = self._feeds.get(n)
            if ph is None:
                raise ValueError(
                    f"run() fed unknown placeholder {n!r}; program "
                    f"feeds are {sorted(self._feeds)}")
            got = tuple(getattr(v, "shape", np.shape(v)))
            want = tuple(ph.shape)
            if got != want:
                raise ValueError(
                    f"feed {n!r} shape {got} != built shape {want} — "
                    "recorded nodes bake build-time dims, so run() "
                    "shapes must match static.data's (build with the "
                    "real batch size; -1 dims become 1)")
        fetches = fetch_list if isinstance(fetch_list, (list, tuple)) \
            else [fetch_list]
        fetch_ids = tuple(id(t) for t in fetches)
        unknown = [i for i, t in zip(fetch_ids, fetches)
                   if i not in self._live]
        if unknown:
            raise ValueError(
                "fetch_list contains tensors the program did not "
                "produce")

        feed_arrays = {n: (v._data if isinstance(v, Tensor)
                           else jnp.asarray(v))
                       for n, v in feed.items()}
        # external inputs: ids consumed but never produced and not feeds
        produced = {i for node in self._nodes for i in node[4]}
        feed_ids = {id(t): n for n, t in self._feeds.items()}
        ext_ids = []
        for node in self._nodes:
            for i in node[3]:
                if i not in produced and i not in feed_ids \
                        and i not in ext_ids:
                    ext_ids.append(i)
        ext_arrays = [self._live[i]._data for i in ext_ids]

        key = (self._version, fetch_ids,
               tuple(sorted((n, tuple(a.shape), str(a.dtype))
                            for n, a in feed_arrays.items())))
        fn = self._exec_cache.get(key)
        if fn is None:
            nodes = list(self._nodes)
            feed_name_by_id = dict(feed_ids)
            ext_index = {i: k for k, i in enumerate(ext_ids)}

            def replay(feed_vals: Dict[str, Any], ext_vals):
                env: Dict[int, Any] = {}
                for i, n in feed_name_by_id.items():
                    env[i] = feed_vals[n]
                for i, k in ext_index.items():
                    env[i] = ext_vals[k]

                def val(i):
                    if i in env:
                        return env[i]
                    return self._live[i]._data   # baked const (rare)

                for name, f, kw, in_ids, out_ids in nodes:
                    args = [val(i) for i in in_ids]
                    out = f(*args, **kw) if kw else f(*args)
                    outs = list(out) if isinstance(out, (tuple, list)) \
                        else [out]
                    for i, o in zip(out_ids, outs):
                        env[i] = o
                return [env[i] for i in fetch_ids]

            fn = jax.jit(replay)
            if len(self._exec_cache) > 64:
                self._exec_cache.clear()
            self._exec_cache[key] = fn
        outs = fn(feed_arrays, ext_arrays)
        return [np.asarray(o) for o in outs]

    # -- reference surface ---------------------------------------------
    def global_block(self):
        return self

    def clone(self, for_test=False):
        p = Program()
        p._nodes = list(self._nodes)
        p._feeds = dict(self._feeds)
        p._live = dict(self._live)
        p._version = self._version
        return p


_main = Program()
_startup = Program()


def default_main_program() -> Program:
    return _main


def default_startup_program() -> Program:
    return _startup


def _active_program() -> Optional[Program]:
    return getattr(_TLS, "program", None)


_GUARD_LOCK = threading.Lock()
_GUARD_COUNT = 0


def _recorder(name, fn, kw, ins, out):
    prog = getattr(_TLS, "program", None)
    if prog is not None:
        prog._record(name, fn, kw, ins, out)


class program_guard:
    """Route op recording into `main_program` (reference
    static.program_guard build-then-run contract). The dispatch hook is
    installed while ANY thread has an open guard (refcounted) and reads
    the thread-local program, so concurrent guards on different threads
    record independently."""

    def __init__(self, main_program=None, startup_program=None):
        self.program = main_program if main_program is not None else _main

    def __enter__(self):
        global _GUARD_COUNT
        from ..ops import dispatch
        self._prev = getattr(_TLS, "program", None)
        _TLS.program = self.program
        if self._prev is None:      # outermost guard on this thread
            with _GUARD_LOCK:
                _GUARD_COUNT += 1
                dispatch.set_static_recorder(_recorder)
        return self

    def __exit__(self, *exc):
        global _GUARD_COUNT
        from ..ops import dispatch
        _TLS.program = self._prev
        if self._prev is None:
            with _GUARD_LOCK:
                _GUARD_COUNT -= 1
                if _GUARD_COUNT == 0:
                    dispatch.set_static_recorder(None)
        return False


class name_scope:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class device_guard:
    def __init__(self, device=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def data(name: str, shape, dtype="float32", lod_level=0):
    """Feed placeholder. Under an active ``program_guard`` this is a
    real placeholder Tensor registered as the program's feed point
    (-1 dims become 1 for build-time shapes; run() feeds must match the
    built shapes). Outside a guard it stays an InputSpec for the
    jit.save export path."""
    prog = _active_program()
    if prog is None:
        return InputSpec(shape, dtype, name)
    concrete = tuple(1 if (d is None or int(d) < 0) else int(d)
                     for d in shape)
    t = Tensor(jnp.zeros(concrete, dtype), stop_gradient=True)
    prog._add_feed(name, t)
    return t


class Executor:
    """Executor.run replays a recorded Program as one jitted function
    of the feeds (reference's L4 graph interpreter, re-expressed as XLA
    compile-and-cache). Callables still run directly, so both styles of
    reference script work."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        if callable(program) and not isinstance(program, Program):
            out = program(**(feed or {}))
            return out if isinstance(out, (list, tuple)) else [out]
        prog = program if isinstance(program, Program) else _main
        if prog._nodes or prog._feeds:
            return prog._execute(feed or {}, fetch_list or [])
        if fetch_list:
            return list(fetch_list)
        return []


def global_scope():
    return {}


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor
                         =None, program=None, **kwargs):
    """Maps onto jit.save: feed_vars carry the input specs; fetch_vars the
    layer whose forward produces them (reference static.io:save_inference_
    model contract, StableHLO artifact)."""
    from .. import jit
    layer = kwargs.get("layer")
    if layer is None and hasattr(fetch_vars, "parameters"):
        layer = fetch_vars
    if layer is None:
        raise ValueError(
            "TPU static shim: pass the Layer as fetch_vars (or layer=) — "
            "there is no global graph to cut feed/fetch out of")
    specs = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    jit.save(layer, path_prefix, input_spec=list(specs))


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    from .. import jit
    loaded = jit.load(path_prefix)
    return [loaded, [], []]


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Reference static.append_backward builds op-level backward into
    the Program. Decision record (module docstring): static-graph
    TRAINING maps onto ``jit.train_step`` / ``jit.to_static`` — the
    differentiated, donated training step IS the compiled program on
    TPU. Use those, or ``static.gradients`` on eager tensors."""
    raise NotImplementedError(
        "paddle.static.append_backward: static-graph training maps onto "
        "jit.train_step / jit.to_static on this framework (see "
        "paddle2_tpu/static/__init__.py decision record); "
        "static.gradients works on eager tensors")


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.tape import grad
    outs = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return list(grad(outs, ins, grad_outputs=target_gradients,
                     retain_graph=True, allow_unused=True))


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input
            =None):
    xs = x if isinstance(x, (list, tuple)) else [x]
    return func(*xs)


class nn:
    """static.nn namespace: the fc/conv helpers map to dygraph layers."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        raise NotImplementedError(
            "static.nn.fc: build models with paddle.nn.Linear — the static "
            "block builder has no TPU counterpart")


class amp:
    """static.amp namespace parity: decorate maps to paddle.amp."""

    @staticmethod
    def decorate(*args, **kwargs):
        from .. import amp as _amp
        return _amp.decorate(*args, **kwargs)
