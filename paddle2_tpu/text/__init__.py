"""paddle.text (reference python/paddle/text/: datasets + viterbi_decode).

viterbi_decode / ViterbiDecoder are fully implemented (lax.scan dynamic
program). Datasets read LOCAL files only (offline build): Imdb consumes
the aclImdb tarball, UCIHousing the housing.data file.
"""

from __future__ import annotations

import os
import re
import tarfile
from typing import List, Optional

import numpy as np

from ..io.dataloader import Dataset
from ..framework.tensor import Tensor
from ..ops.dispatch import apply_op, ensure_tensor

__all__ = ["viterbi_decode", "ViterbiDecoder", "Imdb", "UCIHousing"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """text/viterbi_decode.py parity: returns (scores, paths).

    potentials: [B, T, N] emissions; transition_params: [N, N] (+2 rows/
    cols for BOS/EOS when include_bos_eos_tag); lengths: [B].
    """
    import jax
    import jax.numpy as jnp
    pot = ensure_tensor(potentials)
    trans = ensure_tensor(transition_params)
    lens = ensure_tensor(lengths)

    def f(em, tr, ln):
        B, T, N = em.shape
        if include_bos_eos_tag:
            # reference convention (viterbi_decode.py:47): LAST row/col is
            # the start tag, second-to-last is the stop tag
            bos, eos = N - 1, N - 2
            start = em[:, 0] + tr[bos][None, :]
        else:
            start = em[:, 0]

        def step(carry, t):
            alpha, history_dummy = carry
            # alpha: [B, N]; score via best previous tag
            scores = alpha[:, :, None] + tr[None, :, :] + em[:, t][:, None, :]
            best_prev = jnp.argmax(scores, axis=1)            # [B, N]
            alpha_new = jnp.max(scores, axis=1)               # [B, N]
            # frozen past end-of-sequence
            active = (t < ln)[:, None]
            alpha_new = jnp.where(active, alpha_new, alpha)
            best_prev = jnp.where(active, best_prev,
                                  jnp.arange(N)[None, :])
            return (alpha_new, None), best_prev

        (alpha, _), history = jax.lax.scan(
            step, (start, None), jnp.arange(1, T))
        if include_bos_eos_tag:
            alpha = alpha + tr[:, eos][None, :]
        scores = jnp.max(alpha, axis=-1)
        last = jnp.argmax(alpha, axis=-1)                     # [B]

        def backtrack(carry, bp):
            tag = carry
            prev = jnp.take_along_axis(bp, tag[:, None], 1)[:, 0]
            return prev, tag

        # reverse scan emits tags for times 1..T-1; the final carry is
        # the tag at time 0
        first, path_rev = jax.lax.scan(backtrack, last, history,
                                       reverse=True)
        paths = jnp.concatenate([first[:, None],
                                 jnp.swapaxes(path_rev, 0, 1)],
                                axis=1)                       # [B, T]
        return scores, paths
    return apply_op("viterbi_decode", f, (pot, trans, lens), {},
                    differentiable=False)


class ViterbiDecoder:
    """nn-style wrapper (text/viterbi_decode.py ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        self.transitions = ensure_tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


def _require(path, what):
    if path is None or not os.path.exists(path):
        raise ValueError(
            f"{what}: file not found ({path!r}); this offline build cannot "
            "download datasets — pass the local path")
    return path


class Imdb(Dataset):
    """IMDB sentiment (text/datasets/imdb.py parity; local aclImdb tar)."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        data_file = _require(data_file, "Imdb")
        # vocabulary spans BOTH splits (reference imdb.py builds word_idx
        # over train|test) so train/test token ids agree
        pat_vocab = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        docs: List[List[str]] = []
        labels: List[int] = []
        freq = {}
        with tarfile.open(data_file) as tar:
            for m in tar.getmembers():
                match = pat_vocab.match(m.name)
                if not match:
                    continue
                text = tar.extractfile(m).read().decode(
                    "utf-8", "ignore").lower()
                toks = re.findall(r"[a-z']+", text)
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
                if match.group(1) == mode:
                    docs.append(toks)
                    # reference imdb.py:170-175: pos -> 0, neg -> 1
                    labels.append(0 if match.group(2) == "pos" else 1)
        vocab = [w for w, c in sorted(freq.items(),
                                      key=lambda kv: (-kv[1], kv[0]))
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(t, unk) for t in d],
                                np.int64) for d in docs]
        self.labels = np.asarray(labels, np.int64)

    def __getitem__(self, i):
        return self.docs[i], int(self.labels[i])

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    """Boston housing (text/datasets/uci_housing.py parity; local file)."""

    def __init__(self, data_file=None, mode="train"):
        data_file = _require(data_file, "UCIHousing")
        raw = np.loadtxt(data_file).astype("float32")
        x, y = raw[:, :-1], raw[:, -1:]
        x = (x - x.mean(0)) / np.maximum(x.std(0), 1e-6)
        n = int(len(x) * 0.8)
        if mode == "train":
            self.x, self.y = x[:n], y[:n]
        else:
            self.x, self.y = x[n:], y[n:]

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class _LocalTextDataset:
    """Reference text datasets download corpora; this image has no
    egress, so each dataset consumes a local ``data_file`` and raises a
    pointered error otherwise."""

    name = "dataset"

    def __init__(self, data_file=None, mode="train", **kwargs):
        import os
        if data_file is None or not os.path.exists(data_file):
            raise RuntimeError(
                f"{self.name}: no network egress to download the corpus; "
                "pass data_file=<local copy> (same layout the reference "
                "downloads)")
        self.data_file = data_file
        self.mode = mode
        self._samples = self._load()

    def _load(self):
        raise NotImplementedError

    def __len__(self):
        return len(self._samples)

    def __getitem__(self, idx):
        return self._samples[idx]


class Conll05st(_LocalTextDataset):
    """CoNLL-2005 SRL: tab-separated predicate/argument rows."""
    name = "Conll05st"

    def _load(self):
        out = []
        with open(self.data_file) as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                if parts and parts[0]:
                    out.append(parts)
        return out


class Imikolov(_LocalTextDataset):
    """PTB n-gram corpus (imikolov): yields n-gram tuples."""
    name = "Imikolov"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, **kwargs):
        self.window_size = window_size
        super().__init__(data_file, mode)

    def _load(self):
        out = []
        with open(self.data_file) as f:
            for line in f:
                words = ["<s>"] + line.split() + ["<e>"]
                n = self.window_size
                for i in range(len(words) - n + 1):
                    out.append(tuple(words[i:i + n]))
        return out


class Movielens(_LocalTextDataset):
    """MovieLens ratings: 'user::movie::rating::ts' rows."""
    name = "Movielens"

    def _load(self):
        out = []
        with open(self.data_file) as f:
            for line in f:
                parts = line.strip().split("::")
                if len(parts) >= 3:
                    out.append((int(parts[0]), int(parts[1]),
                                float(parts[2])))
        return out


class WMT14(_LocalTextDataset):
    """WMT'14 en-fr: tab-separated parallel sentences."""
    name = "WMT14"

    def _load(self):
        out = []
        with open(self.data_file) as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                if len(parts) >= 2:
                    out.append((parts[0].split(), parts[1].split()))
        return out


class WMT16(WMT14):
    name = "WMT16"


__all__ += ["Conll05st", "Imikolov", "Movielens", "WMT14", "WMT16"]
