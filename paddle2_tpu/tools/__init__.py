"""Offline operator tooling (``python -m paddle2_tpu.tools.<tool>``).

Kept import-light: these run on a dead job's artifacts (flight-recorder
dumps, gossip dirs), often on a machine with no accelerator.
"""
