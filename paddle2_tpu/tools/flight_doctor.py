"""flight_doctor: merge per-rank flight-recorder dumps into a diagnosis.

::

    python -m paddle2_tpu.tools.flight_doctor /path/to/flight_dir
    python -m paddle2_tpu.tools.flight_doctor --json flight_dir

Reads every ``rank_N.jsonl`` the flight recorder (or the launcher's
collection pass) left behind and answers the three post-mortem
questions a hung or dead gang raises:

1. **Which rank diverged, in which op?** Every rank records its
   collectives with a per-rank sequence number; a correct SPMD program
   dispatches the same collectives in the same order on every rank, so
   the merged per-seq view must agree. The doctor reports the FIRST
   sequence number where it doesn't — a rank that called a different
   op / shape / dtype (op-order desync), and ranks whose rings end
   early ("rank 3 never entered all_reduce seq 412").
2. **Who was slow?** Straggler attribution joins collective-enter
   wall-clock spreads (the last seq every rank reached) with the
   PR 2 step-time gossip dir (``rank.N`` files, ``k * median`` rule).
3. **Where was everyone?** Last known-good step per rank (validated by
   ReliableStep's deferred check), each rank's in-flight collective at
   death, and the dumped thread stacks.

Exit code: 0 when the merged view is consistent, 3 when a desync was
diagnosed (script-friendly: CI chaos drills assert on it).

This module itself is stdlib-only (``load_dump``/``diagnose`` are
importable anywhere the dumps land); running it via ``-m`` pulls the
parent package, which is why auto-recording is guarded on
``PADDLE_TRAINER_ID`` — the doctor must never write into the directory
it is diagnosing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

DESYNC_EXIT = 3
# straggler rule shared with watchdog.StragglerDetector's default
_STRAGGLER_K = 2.0


# ---------------------------------------------------------------- loading
def load_dump(path: str) -> Dict[str, Any]:
    """Parse one ``rank_N.jsonl``: {"header", "events", "stacks"}.
    Unparseable lines are skipped (a dump is evidence, not a contract)."""
    header: Dict[str, Any] = {}
    events: List[Dict[str, Any]] = []
    stacks: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            t = rec.get("type")
            if t == "header":
                header = rec
            elif t == "event":
                events.append(rec)
            elif t == "stacks":
                stacks = rec.get("threads", [])
    events.sort(key=lambda e: e.get("n", 0))
    return {"header": header, "events": events, "stacks": stacks,
            "path": path}


def load_dumps(directory: str) -> Dict[int, Dict[str, Any]]:
    """All ``rank_N.jsonl`` dumps under ``directory``, keyed by rank."""
    out: Dict[int, Dict[str, Any]] = {}
    for name in sorted(os.listdir(directory)):
        if not (name.startswith("rank_") and name.endswith(".jsonl")):
            continue
        stem = name[len("rank_"):-len(".jsonl")]
        if not stem.isdigit():
            continue
        out[int(stem)] = load_dump(os.path.join(directory, name))
    return out


def load_gossip(directory: Optional[str]) -> Dict[int, float]:
    """Step-time gossip files (``rank.N`` -> seconds), empty if absent."""
    times: Dict[int, float] = {}
    if not directory or not os.path.isdir(directory):
        return times
    for name in os.listdir(directory):
        if not name.startswith("rank."):
            continue
        try:
            r = int(name.split(".", 1)[1])
            with open(os.path.join(directory, name)) as f:
                times[r] = float(f.read().strip())
        except (OSError, ValueError):
            continue
    return times


def load_quarantine(directory: Optional[str]) -> List[Dict[str, Any]]:
    """Verdicts in the persistent quarantine store (``q_<node>.json``
    under ``PADDLE_QUARANTINE_DIR``), oldest first; empty if absent.
    Read directly (stdlib-only) so the doctor never imports the
    package it is diagnosing."""
    out: List[Dict[str, Any]] = []
    if not directory or not os.path.isdir(directory):
        return out
    for name in sorted(os.listdir(directory)):
        if not (name.startswith("q_") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                out.append(json.load(f))
        except (OSError, ValueError):
            continue
    return sorted(out, key=lambda r: r.get("ts", 0))


def load_elastic_events(directory: Optional[str]) -> List[Dict[str, Any]]:
    """The launcher's ``elastic.*`` event stream
    (``elastic_events.jsonl``: rendezvous outcomes, scale events,
    respawns, restart latency), empty if absent or unreadable."""
    out: List[Dict[str, Any]] = []
    if not directory:
        return out
    path = os.path.join(directory, "elastic_events.jsonl")
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and \
                        str(rec.get("kind", "")).startswith("elastic."):
                    out.append(rec)
    except OSError:
        pass
    return out


# ---------------------------------------------------------------- analysis
def _collective_sig(ev: Dict[str, Any]) -> Tuple:
    shape = ev.get("shape")
    if isinstance(shape, list):
        shape = tuple(shape)
    return (ev.get("op"), shape, ev.get("dtype"), ev.get("group"))


def _sig_str(sig: Tuple) -> str:
    op, shape, dtype, group = sig
    bits = [str(op)]
    if shape is not None:
        bits.append(f"shape={tuple(shape)}")
    if dtype:
        bits.append(f"dtype={dtype}")
    if group:
        bits.append(f"group={group}")
    return " ".join(bits)


def _rank_list(ranks) -> str:
    return ",".join(str(r) for r in sorted(ranks))


def diagnose(dumps: Dict[int, Dict[str, Any]],
             gossip: Optional[Dict[int, float]] = None,
             elastic: Optional[List[Dict[str, Any]]] = None,
             quarantine: Optional[List[Dict[str, Any]]] = None
             ) -> Dict[str, Any]:
    """Merge per-rank dumps into a structured diagnosis (the JSON the
    CLI prints with ``--json``; the text report renders the same dict).
    ``elastic`` is the launcher's scale-event timeline — evidence of
    WHY the world looks the way it does (rescales, give-ups, restart
    latency), kept in the report verbatim (newest 20)."""
    gossip = gossip or {}
    ranks = sorted(dumps)
    report: Dict[str, Any] = {
        "ranks": ranks,
        "world": max((d["header"].get("world", 0) for d in dumps.values()),
                     default=0),
        "reasons": {r: dumps[r]["header"].get("reason") for r in ranks},
        "generations": {r: dumps[r]["header"].get("generation", 0)
                        for r in ranks},
        "missing_dumps": [],
        "stale_dumps": [],
        "last_good_step": {},
        "inflight": {},
        "desyncs": [],
        "guilty": [],
        "straggler": {},
        "elastic_events": list(elastic or [])[-20:],
        "quarantine": list(quarantine or []),
        "nodes": {r: dumps[r]["header"].get("node") for r in ranks},
        "sdc": [],
        "serving": {},
        "ps": {},
        "moe": {},
        "sep": {},
    }
    # serving plane (PR 11): scheduler admit/evict/requeue/shed, engine
    # decode steps, failures/failovers, and hot-swap events — per-event
    # counts plus the newest few, so a serving crash post-mortem shows
    # what the reliability plane was doing when the engine died
    serving_counts: Dict[str, int] = {}
    serving_tail: List[Dict[str, Any]] = []
    for r in ranks:
        for ev in dumps[r]["events"]:
            if ev.get("kind") != "serving":
                continue
            name = ev.get("event", "?")
            serving_counts[name] = serving_counts.get(name, 0) + 1
            serving_tail.append({"rank": r, **{k: v for k, v in ev.items()
                                               if k != "kind"}})
    if serving_counts:
        report["serving"] = {"counts": serving_counts,
                             "last": serving_tail[-10:]}
    # parameter-server plane (ISSUE 18): pull/push spans plus the
    # failure narrative (server_kill -> stale_read/retry -> failover ->
    # resync), each span carrying shard + server ids so a dead drill is
    # attributable to a specific modeled host
    ps_counts: Dict[str, int] = {}
    ps_tail: List[Dict[str, Any]] = []
    for r in ranks:
        for ev in dumps[r]["events"]:
            if ev.get("kind") != "ps":
                continue
            name = ev.get("event", "?")
            ps_counts[name] = ps_counts.get(name, 0) + 1
            ps_tail.append({"rank": r, **{k: v for k, v in ev.items()
                                          if k != "kind"}})
    if ps_counts:
        report["ps"] = {"counts": ps_counts, "last": ps_tail[-10:]}
    # expert-parallel MoE plane (ISSUE 19): the failure narrative
    # (host_kill -> failover -> resync), router_collapse trips, and
    # ledger_violation markers, each span carrying expert + host ids so
    # a dead drill is attributable to a specific modeled expert host
    moe_counts: Dict[str, int] = {}
    moe_tail: List[Dict[str, Any]] = []
    for r in ranks:
        for ev in dumps[r]["events"]:
            if ev.get("kind") != "moe":
                continue
            name = ev.get("event", "?")
            moe_counts[name] = moe_counts.get(name, 0) + 1
            moe_tail.append({"rank": r, **{k: v for k, v in ev.items()
                                           if k != "kind"}})
    if moe_counts:
        report["moe"] = {"counts": moe_counts, "last": moe_tail[-10:]}
    # sequence-parallel plane (ISSUE 20): the failure narrative
    # (host_kill -> failover -> ring_reform -> resync) plus
    # lse_ledger_breach markers, each span carrying shard + host ids
    # so a dead ring pass is attributable to a specific modeled host
    sep_counts: Dict[str, int] = {}
    sep_tail: List[Dict[str, Any]] = []
    for r in ranks:
        for ev in dumps[r]["events"]:
            if ev.get("kind") != "sep":
                continue
            name = ev.get("event", "?")
            sep_counts[name] = sep_counts.get(name, 0) + 1
            sep_tail.append({"rank": r, **{k: v for k, v in ev.items()
                                           if k != "kind"}})
    if sep_counts:
        report["sep"] = {"counts": sep_counts, "last": sep_tail[-10:]}
    # SDC evidence: fingerprint-vote mismatches and self-evictions the
    # workers recorded. Deduped by (rank, step) — every voter records
    # the same verdict; the report wants the verdict once per witness.
    for r in ranks:
        for ev in dumps[r]["events"]:
            kind = ev.get("kind")
            if kind == "sdc.fingerprint_mismatch":
                report["sdc"].append({
                    "witness": r, "step": ev.get("step"),
                    "attempt": ev.get("attempt"),
                    "suspects": ev.get("suspects"),
                    "digests": ev.get("digests")})
            elif kind in ("sdc.evict", "elastic.quarantine"):
                report["sdc"].append({
                    "witness": r, "kind": kind,
                    "step": ev.get("step"), "host": ev.get("host"),
                    "reason": ev.get("reason")})
    world = report["world"] or (max(ranks) + 1 if ranks else 0)
    report["missing_dumps"] = [r for r in range(world) if r not in dumps]
    # restart-generation fence for the ANALYSIS itself: a surviving dump
    # from a PRE-restart generation records a different incarnation of
    # the program — its cseq counters restarted, so joining it against
    # current-generation rings would convict an innocent rank. Stale
    # dumps stay in the inventory but are excluded from the cross-rank
    # sequence join and straggler arrival.
    current_gen = max((int(g or 0)
                       for g in report["generations"].values()),
                      default=0)
    report["current_generation"] = current_gen
    report["stale_dumps"] = sorted(
        r for r, g in report["generations"].items()
        if int(g or 0) < current_gen)

    # per-rank collective ledgers
    enters: Dict[int, Dict[int, Dict[str, Any]]] = {}
    exits: Dict[int, set] = {}
    for r in ranks:
        enters[r] = {}
        exits[r] = set()
        last_good = None
        for ev in dumps[r]["events"]:
            kind = ev.get("kind")
            if kind == "collective_enter":
                enters[r][int(ev["cseq"])] = ev
            elif kind == "collective_exit":
                exits[r].add(int(ev["cseq"]))
            elif kind == "step_ok":
                s = ev.get("step")
                if s is not None and (last_good is None or s > last_good):
                    last_good = s
        report["last_good_step"][r] = last_good
        # in-flight at death: entered, never exited (newest last)
        report["inflight"][r] = [
            {"cseq": c, "desc": _sig_str(_collective_sig(e)),
             "t": e.get("t")}
            for c, e in sorted(enters[r].items())
            if c not in exits[r]]

    # the comparable window: per-rank cseq is contiguous, but the ring
    # drops old events — only compare seqs every surviving ring holds
    stale = set(report["stale_dumps"])
    with_colls = [r for r in ranks if enters[r] and r not in stale]
    if len(with_colls) >= 2:
        lo = max(min(enters[r]) for r in with_colls)
        hi = max(max(enters[r]) for r in with_colls)
        first_div = None
        for s in range(lo, hi + 1):
            present = {r: enters[r][s] for r in with_colls
                       if s in enters[r]}
            absent = [r for r in with_colls if s not in enters[r]]
            sigs: Dict[Tuple, List[int]] = {}
            for r, ev in present.items():
                sigs.setdefault(_collective_sig(ev), []).append(r)
            entry = None
            if len(sigs) > 1:
                # op-order / shape / dtype desync: minority is guilty
                ordered = sorted(sigs.items(), key=lambda kv: -len(kv[1]))
                majority_sig, majority_ranks = ordered[0]
                minority = [(sig, rs) for sig, rs in ordered[1:]]
                entry = {
                    "seq": s, "kind": "mismatch",
                    "majority": {"ranks": sorted(majority_ranks),
                                 "desc": _sig_str(majority_sig)},
                    "minority": [{"ranks": sorted(rs),
                                  "desc": _sig_str(sig)}
                                 for sig, rs in minority],
                }
                for _, rs in minority:
                    for r in rs:
                        if r not in report["guilty"]:
                            report["guilty"].append(r)
            elif absent and present:
                # ranks whose ring ENDS before s: they never entered
                tail_missing = [r for r in absent if max(enters[r]) < s]
                if tail_missing:
                    sig, rs = next(iter(sigs.items()))
                    entry = {
                        "seq": s, "kind": "never_entered",
                        "entered": {"ranks": sorted(present),
                                    "desc": _sig_str(sig)},
                        "never_entered": sorted(tail_missing),
                        "last_seen": {
                            r: {"cseq": max(enters[r]),
                                "desc": _sig_str(_collective_sig(
                                    enters[r][max(enters[r])]))}
                            for r in tail_missing},
                    }
                    for r in tail_missing:
                        if r not in report["guilty"]:
                            report["guilty"].append(r)
            if entry is not None:
                if first_div is None:
                    first_div = s
                report["desyncs"].append(entry)
                if len(report["desyncs"]) >= 10:
                    break
        report["first_divergence_seq"] = first_div

        # arrival spread at the last seq EVERY rank entered
        common_hi = min(max(enters[r]) for r in with_colls)
        common = None
        for s in range(common_hi, lo - 1, -1):
            if all(s in enters[r] for r in with_colls):
                common = s
                break
        if common is not None:
            arrivals = {r: enters[r][common].get("t")
                        for r in with_colls}
            if all(t is not None for t in arrivals.values()):
                t0 = min(arrivals.values())
                report["straggler"]["arrival"] = {
                    "seq": common,
                    "desc": _sig_str(_collective_sig(
                        enters[with_colls[0]][common])),
                    "delays": {r: round(arrivals[r] - t0, 6)
                               for r in with_colls},
                    "slowest": max(arrivals, key=arrivals.get),
                }

    # gossip-based straggler suspects (k * median of last step times)
    if len(gossip) >= 2:
        vals = sorted(gossip.values())
        mid = len(vals) // 2
        median = (vals[mid] if len(vals) % 2
                  else 0.5 * (vals[mid - 1] + vals[mid]))
        suspects = sorted((r for r, t in gossip.items()
                           if median > 0 and t > _STRAGGLER_K * median),
                          key=lambda r: -gossip[r])
        report["straggler"]["gossip"] = {
            "times": {r: gossip[r] for r in sorted(gossip)},
            "median": median, "suspects": suspects,
        }
    return report


# ---------------------------------------------------------------- report
def _format_elastic_timeline(report: Dict[str, Any]) -> List[str]:
    ev = report.get("elastic_events") or []
    if not ev:
        return []
    L = ["ELASTIC TIMELINE (launcher)"]
    for e in ev:
        extra = {k: v for k, v in e.items()
                 if k not in ("type", "kind", "t")}
        detail = " ".join(f"{k}={v}" for k, v in sorted(extra.items()))
        L.append(f"  t={e.get('t', 0):.3f} {e.get('kind')} {detail}")
    return L


def format_report(report: Dict[str, Any], directory: str) -> str:
    L: List[str] = []
    ranks = report["ranks"]
    L.append(f"flight_doctor: merged {len(ranks)} rank dump(s) from "
             f"{directory}")
    if not ranks:
        L.append("  no rank_N.jsonl dumps found — is PADDLE_FLIGHT_DIR "
                 "set on the workers? (a SIGKILLed gang leaves none; "
                 "the launcher timeline below may still explain it)")
        L.extend(_format_elastic_timeline(report))
        return "\n".join(L)
    L.append(f"  ranks: {_rank_list(ranks)} (world "
             f"{report['world'] or '?'}) generations: "
             + " ".join(f"r{r}={g}"
                        for r, g in sorted(report["generations"].items())))
    for r in ranks:
        L.append(f"  rank {r}: dumped for {report['reasons'][r]!r}")
    if report["missing_dumps"]:
        L.append(f"  MISSING dumps from rank(s) "
                 f"{_rank_list(report['missing_dumps'])} — reaped "
                 f"before dumping (SIGKILL/OOM?); their silence is "
                 f"itself a clue")
    if report["stale_dumps"]:
        L.append(f"  STALE dumps from rank(s) "
                 f"{_rank_list(report['stale_dumps'])} (restart "
                 f"generation < {report.get('current_generation')}): "
                 f"pre-restart evidence, excluded from the sequence "
                 f"join")
    lg = report["last_good_step"]
    if any(v is not None for v in lg.values()):
        L.append("  last known-good step: "
                 + " ".join(f"rank{r}={lg[r]}"
                            for r in ranks if lg[r] is not None))
    inflight = {r: v for r, v in report["inflight"].items() if v}
    if inflight:
        L.append("  in-flight at death:")
        for r, ops in sorted(inflight.items()):
            newest = ops[-1]
            L.append(f"    rank {r}: seq {newest['cseq']} "
                     f"{newest['desc']} (entered, never exited)")

    if report["desyncs"]:
        L.append("DESYNC DIAGNOSIS")
        for d in report["desyncs"]:
            if d["kind"] == "mismatch":
                L.append(f"  seq {d['seq']}: ranks "
                         f"{_rank_list(d['majority']['ranks'])} called "
                         f"{d['majority']['desc']}")
                for m in d["minority"]:
                    L.append(f"    but rank(s) {_rank_list(m['ranks'])} "
                             f"called {m['desc']} — op-order/shape/"
                             f"dtype desync")
            else:
                L.append(f"  seq {d['seq']}: rank(s) "
                         f"{_rank_list(d['never_entered'])} never "
                         f"entered {d['entered']['desc']} (ranks "
                         f"{_rank_list(d['entered']['ranks'])} did)")
                for r, last in sorted(d["last_seen"].items()):
                    L.append(f"    rank {r} last dispatched seq "
                             f"{last['cseq']}: {last['desc']}")
        if report["guilty"]:
            L.append(f"  verdict: rank(s) "
                     f"{_rank_list(report['guilty'])} diverged first "
                     f"(seq {report.get('first_divergence_seq')}) — "
                     f"inspect their thread stacks in the dump")
    else:
        L.append("collective sequences: consistent across ranks "
                 "(no desync in the retained window)")

    s = report.get("straggler", {})
    if s:
        L.append("STRAGGLER ATTRIBUTION")
        if "arrival" in s:
            a = s["arrival"]
            delays = ", ".join(f"rank{r}=+{a['delays'][r]:.3f}s"
                               for r in sorted(a["delays"]))
            L.append(f"  arrival at seq {a['seq']} ({a['desc']}): "
                     f"{delays}; slowest: rank {a['slowest']}")
        if "gossip" in s:
            g = s["gossip"]
            times = ", ".join(f"rank{r}={g['times'][r]:.3f}s"
                              for r in sorted(g["times"]))
            L.append(f"  step-time gossip: {times} "
                     f"(median {g['median']:.3f}s)")
            if g["suspects"]:
                L.append(f"  suspected straggler rank(s): "
                         f"{_rank_list(g['suspects'])} "
                         f"(step time > {_STRAGGLER_K:g} x median)")

    L.extend(_format_serving(report))
    L.extend(_format_ps(report))
    L.extend(_format_moe(report))
    L.extend(_format_sep(report))
    L.extend(_format_quarantine(report))
    L.extend(_format_elastic_timeline(report))
    return "\n".join(L)


def _format_ps(report: Dict[str, Any]) -> List[str]:
    """PARAMETER SERVER section: the sharded-table plane's spans —
    pull/push volume plus the failure narrative (``server_kill`` ->
    ``stale_read``/retry -> ``failover`` -> ``resync``). The shard and
    server ids lead each event so a drill post-mortem attributes every
    promotion and resync to a specific modeled host."""
    psr = report.get("ps") or {}
    if not psr:
        return []
    L = ["PARAMETER SERVER"]
    counts = psr.get("counts") or {}
    L.append("  events: " + " ".join(f"{k}={counts[k]}"
                                     for k in sorted(counts)))
    for ev in (psr.get("last") or [])[-10:]:
        rank = ev.get("rank", "?")
        lead = []
        if "shard" in ev:
            lead.append(f"shard={ev['shard']}")
        if "server" in ev:
            lead.append(f"server={ev['server']}")
        if "t" in ev:
            lead.append(f"t={ev['t']:.9f}")
        detail = " ".join(f"{k}={ev[k]}" for k in sorted(ev)
                          if k not in ("rank", "event", "shard",
                                       "server", "t"))
        L.append(f"  rank {rank}: {ev.get('event', '?')} "
                 + " ".join(lead + [detail]).strip())
    return L


def _format_moe(report: Dict[str, Any]) -> List[str]:
    """EXPERT-PARALLEL MOE section: the expert-fleet plane's spans —
    the failure narrative (``host_kill`` -> ``failover`` -> ``resync``)
    plus ``router_collapse`` and ``ledger_violation`` markers. The
    expert and host ids lead each event so a drill post-mortem
    attributes every promotion and resync to a specific modeled expert
    host."""
    mr = report.get("moe") or {}
    if not mr:
        return []
    L = ["EXPERT-PARALLEL MOE"]
    counts = mr.get("counts") or {}
    L.append("  events: " + " ".join(f"{k}={counts[k]}"
                                     for k in sorted(counts)))
    for ev in (mr.get("last") or [])[-10:]:
        rank = ev.get("rank", "?")
        lead = []
        if "expert" in ev:
            lead.append(f"expert={ev['expert']}")
        if "host" in ev:
            lead.append(f"host={ev['host']}")
        if "t" in ev:
            lead.append(f"t={ev['t']:.9f}")
        detail = " ".join(f"{k}={ev[k]}" for k in sorted(ev)
                          if k not in ("rank", "event", "expert",
                                       "host", "t"))
        L.append(f"  rank {rank}: {ev.get('event', '?')} "
                 + " ".join(lead + [detail]).strip())
    return L


def _format_sep(report: Dict[str, Any]) -> List[str]:
    """SEQUENCE PARALLEL section: what the long-context plane recorded
    — host_kill / failover / ring_reform / resync spans and
    lse_ledger_breach markers — per-event counts plus the newest few,
    each carrying shard + host ids and the virtual clock stamp, so an
    aborted ring pass post-mortem shows which host died mid-rotation
    and when the ring re-formed."""
    sp = report.get("sep") or {}
    if not sp:
        return []
    L = ["SEQUENCE PARALLEL"]
    counts = sp.get("counts") or {}
    L.append("  events: " + " ".join(f"{k}={counts[k]}"
                                     for k in sorted(counts)))
    for ev in (sp.get("last") or [])[-10:]:
        rank = ev.get("rank", "?")
        lead = []
        if "shard" in ev:
            lead.append(f"shard={ev['shard']}")
        if "host" in ev:
            lead.append(f"host={ev['host']}")
        if "t" in ev:
            lead.append(f"t={ev['t']:.9f}")
        detail = " ".join(f"{k}={ev[k]}" for k in sorted(ev)
                          if k not in ("rank", "event", "shard",
                                       "host", "t"))
        L.append(f"  rank {rank}: {ev.get('event', '?')} "
                 + " ".join(lead + [detail]).strip())
    return L


def _format_serving(report: Dict[str, Any]) -> List[str]:
    """SERVING section: what the serving reliability plane recorded —
    admit/evict/requeue/shed counts, decode steps, engine failures,
    failovers, hot-swap stages, and the fleet-KV ladder's spans
    (``kv_spill``/``spill_fetch``/``migrate``/``migrate_declined``/
    ``migration_dropped``) — plus the newest events with their trace
    id and clock stamp leading, so a flight dump JOINS the
    request-tracing streams (``serve_doctor``'s trace_rank_N.jsonl)
    on ``tid``/``t`` instead of dead-ending at per-event counts."""
    sv = report.get("serving") or {}
    if not sv:
        return []
    L = ["SERVING"]
    counts = sv.get("counts") or {}
    L.append("  events: " + " ".join(f"{k}={counts[k]}"
                                     for k in sorted(counts)))
    for ev in (sv.get("last") or [])[-10:]:
        rank = ev.get("rank", "?")
        # the JOIN KEYS lead: tid (stable across failover re-keying,
        # shared with the trace streams) and the virtual-clock stamp
        join = []
        if "tid" in ev:
            join.append(f"tid={ev['tid']}")
        elif "tids" in ev:
            join.append(f"tids={ev['tids']}")
        if "t" in ev:
            join.append(f"t={ev['t']:.9f}")
        detail = " ".join(f"{k}={ev[k]}" for k in sorted(ev)
                          if k not in ("rank", "event", "tid", "tids",
                                       "t"))
        L.append(f"  rank {rank}: {ev.get('event', '?')} "
                 + " ".join(join + [detail]).strip())
    return L


def _format_quarantine(report: Dict[str, Any]) -> List[str]:
    """QUARANTINE section: the persistent store's verdicts plus the
    workers' sdc.* evidence (fingerprint-vote mismatches, evictions) —
    the silent-data-corruption half of the post-mortem."""
    verdicts = report.get("quarantine") or []
    sdc = report.get("sdc") or []
    if not verdicts and not sdc:
        return []
    L = ["QUARANTINE"]
    for v in verdicts:
        age = ""
        if v.get("ts"):
            age = f" ({time.time() - v['ts']:.0f}s ago)"
        ev = v.get("evidence") or {}
        detail = " ".join(f"{k}={ev[k]}" for k in sorted(ev)
                          if isinstance(ev[k], (str, int, float)))
        L.append(f"  node {v.get('host')}: {v.get('reason')}"
                 f"{' rank ' + str(v['rank']) if v.get('rank') is not None else ''}"
                 f"{age}{' — ' + detail if detail else ''}")
    seen = set()
    for e in sdc:
        key = (e.get("kind"), e.get("step"), str(e.get("suspects")),
               e.get("host"))
        if key in seen:
            continue
        seen.add(key)
        if e.get("kind") in ("sdc.evict", "elastic.quarantine"):
            L.append(f"  rank {e['witness']} recorded {e['kind']}: "
                     f"host {e.get('host')} ({e.get('reason')})")
        else:
            L.append(f"  fingerprint mismatch at step {e.get('step')}"
                     f" (witness rank {e['witness']}): suspect rank(s) "
                     f"{e.get('suspects')} digests {e.get('digests')}")
    if verdicts:
        L.append("  a quarantined node is excluded from every "
                 "re-formation until its q_<node>.json is removed")
    return L


# ---------------------------------------------------------------- CLI
def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="paddle2_tpu.tools.flight_doctor",
        description="merge per-rank flight-recorder dumps and diagnose "
                    "cross-rank desyncs, stragglers, and last-known-good "
                    "state")
    p.add_argument("flight_dir", nargs="?",
                   default=os.environ.get("PADDLE_FLIGHT_DIR"),
                   help="directory holding rank_N.jsonl dumps "
                        "(default: $PADDLE_FLIGHT_DIR)")
    p.add_argument("--gossip-dir",
                   default=os.environ.get("PADDLE_STEP_GOSSIP_DIR"),
                   help="step-time gossip dir for straggler attribution "
                        "(default: $PADDLE_STEP_GOSSIP_DIR)")
    p.add_argument("--quarantine-dir",
                   default=os.environ.get("PADDLE_QUARANTINE_DIR"),
                   help="persistent node-quarantine store for the "
                        "QUARANTINE section "
                        "(default: $PADDLE_QUARANTINE_DIR)")
    p.add_argument("--json", action="store_true",
                   help="emit the structured diagnosis as JSON")
    args = p.parse_args(argv)
    if not args.flight_dir:
        p.error("no flight dir: pass one or set PADDLE_FLIGHT_DIR")
    if not os.path.isdir(args.flight_dir):
        print(f"flight_doctor: {args.flight_dir!r} is not a directory",
              file=sys.stderr)
        return 2
    dumps = load_dumps(args.flight_dir)
    report = diagnose(dumps, load_gossip(args.gossip_dir),
                      load_elastic_events(args.flight_dir),
                      load_quarantine(args.quarantine_dir))
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(format_report(report, args.flight_dir))
    return DESYNC_EXIT if report["desyncs"] else 0


if __name__ == "__main__":
    sys.exit(main())
