"""perf_doctor: triage "where did the step time go" from metrics streams.

The performance sibling of ``flight_doctor``::

    python -m paddle2_tpu.tools.perf_doctor /path/to/metrics_dir
    python -m paddle2_tpu.tools.perf_doctor diff BASELINE_DIR NEW_DIR
    python -m paddle2_tpu.tools.perf_doctor --json metrics_dir

Reads the per-rank JSONL streams the always-on metrics plane writes
(``metrics_rank_N.jsonl`` under ``PADDLE_METRICS_DIR``) and answers the
three triage questions a slow training job raises:

1. **Where does the step go?** Per-rank step-time breakdown — mean
   input-wait / compute / collective / host seconds (components that by
   construction sum to the step total), tokens/s, plus the reliability
   counter set (retries, SDC convictions, quarantines, worker respawns,
   compile-cache hits) so the detect->recover loop is VISIBLE, not just
   logged post-mortem.
2. **Who is slow, and why?** Straggler attribution: ranks whose mean
   step time exceeds ``k x median`` across ranks; slow-INPUT
   attribution: ranks whose input-wait share of the step is an outlier
   (a straggler whose extra time is input wait has a data problem, not
   a chip problem).
3. **What regressed?** ``diff A B`` aligns two streams and names the
   top regressed breakdown component by mean per-step delta, exiting
   ``REGRESSION_EXIT`` (4) when the total regression passes the
   threshold — the CI-gating primitive.

Joins (optional, both best-effort):

* ``--flight-dir`` — flight-recorder rank dumps: step retries, worker
  respawns, chaos events, and dump reasons land in the report, so one
  triage view correlates perf and health;
* ``--trace`` — a merged chrome trace (``profiler.merge_traces``
  output): per-lane ``ProfileStep#`` span means cross-check the
  metrics-plane step totals against the profiler's deep view.

Stdlib-only (the same posture as ``flight_doctor``): runs anywhere the
JSONL lands, never imports jax.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
from typing import Any, Dict, List, Optional, Tuple

REGRESSION_EXIT = 4
COMPONENTS = ("input_wait_s", "compute_s", "collective_s", "host_s")
_COMPONENT_LABEL = {"input_wait_s": "input-wait", "compute_s": "compute",
                    "collective_s": "collective", "host_s": "host"}
# straggler rule shared with flight_doctor / watchdog defaults
_STRAGGLER_K = 2.0
# reliability counters surfaced in every report (when present)
_RELIABILITY_COUNTERS = (
    "steps_total", "step_retries_total", "reliability_snapshots_total",
    "reliability_restores_total", "sdc_mismatches_total",
    "sdc_convictions_total", "quarantines_total",
    "data_worker_respawns_total", "amp_skipped_steps_total",
    "compiles_total", "compile_cache_hits_total",
    "train_step_compiles_total", "checkpoint_saves_total",
    "checkpoint_restores_total", "checkpoint_save_failures_total",
    "checkpoint_restore_failures_total",
    # serving reliability plane (PR 11): shed/retry/failover lanes —
    # a serving regression often shows up here before it shows up in
    # step time (sheds eat requests, failovers eat re-prefill compute)
    "serving_shed_total", "serving_deadline_exceeded_total",
    "serving_retries_total", "serving_evictions_total",
    "serving_engine_failures_total", "serving_failovers_total",
    "serving_recovered_seqs_total", "serving_table_corruptions_total",
    "serving_hot_swaps_total",
    # SLO ledger (ISSUE 13): good/bad requests against the configured
    # TTFT/TPOT/e2e targets — the burn-rate gauge rides the snapshot
    "serving_slo_good_total", "serving_slo_bad_total",
    # serving throughput plane (ISSUE 14): a prefix-cache hit-rate or
    # speculation acceptance-rate regression is a silent KV-bytes /
    # tokens-per-second regression — surfacing the raw counters in
    # diff makes it NAMEABLE before the modeled throughput moves
    "serving_prefix_hits_total", "serving_prefix_misses_total",
    "serving_prefix_hit_blocks_total",
    "serving_spec_accepted_total", "serving_spec_rejected_total",
    # fleet-global KV ladder (ISSUE 16): tier traffic — a spill surge
    # is HBM cache pressure, a host/peer-fetch surge is the pressure
    # being absorbed (fetch, not recompute), migrated blocks are
    # failovers resuming without re-prefill
    "serving_kv_spill_blocks_total", "serving_kv_fetch_host_blocks_total",
    "serving_kv_fetch_peer_blocks_total", "serving_kv_migrated_blocks_total",
    # parameter-server plane (ISSUE 18): pull/push volume, server
    # failures vs failovers (they should pair 1:1 per dead primary),
    # stale reads (bounded-staleness degradation, not an error — but a
    # surge means shards are re-forming), resyncs (corrupt deltas or
    # follower recruits), and the staleness gauge
    "ps_pulls_total", "ps_pushes_total", "ps_server_failures_total",
    "ps_failovers_total", "ps_stale_reads_total", "ps_resyncs_total",
    # expert-parallel MoE plane (ISSUE 19): routed vs capacity-dropped
    # picks (a drop surge is a capacity-factor/balance problem, not an
    # error — the ledger still closes), host failures vs failovers
    # (pair per dead primary), resyncs (follower recruits), and router
    # collapses (typed watchdog trips — ALWAYS worth reading back)
    "moe_steps_total", "moe_tokens_routed_total",
    "moe_tokens_dropped_total", "moe_expert_fetches_total",
    "moe_expert_stores_total", "moe_expert_host_failures_total",
    "moe_failovers_total", "moe_resyncs_total",
    "moe_router_collapses_total",
    # sequence-parallel plane (ISSUE 20): ring passes per step (one
    # per layer per attention call — a shortfall vs steps means passes
    # are aborting), host failures vs failovers (pair per dead
    # primary), ring re-formations (each one is a topology change —
    # read the flight recorder), replayed steps (chaos healed through
    # ReliableStep), resyncs (follower recruits), and LSE-merge ledger
    # audits (one per pass; fewer than passes means audits are skipped)
    "sep_steps_total", "sep_ring_passes_total",
    "sep_ring_reformations_total", "sep_replayed_steps_total",
    "sep_lse_audits_total", "sep_host_failures_total",
    "sep_failovers_total", "sep_resyncs_total",
)


# ---------------------------------------------------------------- loading
def load_stream(path: str) -> Dict[str, Any]:
    """Parse one ``metrics_rank_N.jsonl``: step records in order plus
    the LAST metrics snapshot (counters are cumulative — the newest
    snapshot is the total). Unparseable lines are skipped."""
    steps: List[Dict[str, Any]] = []
    snapshot: Dict[str, Any] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            t = rec.get("type")
            if t == "step":
                steps.append(rec)
            elif t == "metrics":
                snapshot = rec
    steps.sort(key=lambda r: r.get("step", 0))
    return {"steps": steps, "snapshot": snapshot, "path": path}


def load_streams(directory: str) -> Dict[int, Dict[str, Any]]:
    """Every ``metrics_rank_N.jsonl`` under ``directory``, keyed by
    rank. A single FILE path is accepted too (rank parsed from the
    name, else 0)."""
    out: Dict[int, Dict[str, Any]] = {}
    if os.path.isfile(directory):
        out[_rank_of(os.path.basename(directory))] = load_stream(directory)
        return out
    if not os.path.isdir(directory):
        return out
    for name in sorted(os.listdir(directory)):
        if name.startswith("metrics_rank_") and name.endswith(".jsonl"):
            out[_rank_of(name)] = load_stream(
                os.path.join(directory, name))
    return out


def _rank_of(name: str) -> int:
    stem = name[len("metrics_rank_"):-len(".jsonl")] \
        if name.startswith("metrics_rank_") else ""
    return int(stem) if stem.isdigit() else 0


def _counter_total(snapshot: Dict[str, Any], name: str) -> float:
    """Sum a counter over all its label sets in a metrics snapshot."""
    series = (snapshot.get("counters") or {}).get(name)
    if not isinstance(series, dict):
        return 0.0
    return sum(v for v in series.values()
               if isinstance(v, (int, float)))


def load_flight_counters(flight_dir: Optional[str]) -> Dict[str, Any]:
    """Best-effort join with flight-recorder dumps: event-kind counts
    and per-rank dump reasons. Parsing is delegated to
    ``flight_doctor.load_dumps`` — ONE reader owns the dump format."""
    out: Dict[str, Any] = {"reasons": {}, "event_counts": {}}
    if not flight_dir or not os.path.isdir(flight_dir):
        return out
    from . import flight_doctor
    try:
        dumps = flight_doctor.load_dumps(flight_dir)
    except OSError:
        return out
    for rank, dump in dumps.items():
        out["reasons"][rank] = dump["header"].get("reason")
        for ev in dump["events"]:
            k = ev.get("kind")
            out["event_counts"][k] = out["event_counts"].get(k, 0) + 1
    return out


def load_trace_steps(trace_path: Optional[str]) -> Dict[str, Any]:
    """Per-lane ``ProfileStep#`` span stats from a (merged) chrome
    trace — the profiler's view of the same step cadence."""
    out: Dict[str, Any] = {}
    if not trace_path or not os.path.isfile(trace_path):
        return out
    try:
        with open(trace_path) as f:
            trace = json.load(f)
    except (OSError, ValueError):
        return out
    lanes: Dict[Any, str] = {}
    spans: Dict[Any, List[float]] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            lanes[e.get("pid")] = (e.get("args") or {}).get("name")
        elif str(e.get("name", "")).startswith("ProfileStep#"):
            spans.setdefault(e.get("pid"), []).append(
                float(e.get("dur", 0.0)) / 1e6)
    for pid, durs in sorted(spans.items()):
        out[str(lanes.get(pid, pid))] = {
            "steps": len(durs),
            "mean_step_s": sum(durs) / len(durs) if durs else 0.0}
    return out


# ---------------------------------------------------------------- analysis
def hist_quantile(buckets: List[Optional[float]], counts: List[float],
                  q: float) -> Optional[float]:
    """Prometheus-style ``histogram_quantile``: cumulative per-bucket
    counts (``None`` upper bound = +Inf) -> the ``q``-quantile
    estimate, linearly interpolated inside the owning bucket. The
    +Inf bucket returns the highest finite bound (the standard
    convention — the true value is only known to be beyond it)."""
    if not counts or counts[-1] <= 0 or len(buckets) != len(counts):
        return None
    total = counts[-1]
    target = q / 100.0 * total
    prev_cum, prev_ub = 0.0, 0.0
    for ub, cum in zip(buckets, counts):
        if cum >= target:
            if ub is None:                 # +Inf bucket owns it
                return prev_ub
            in_bucket = cum - prev_cum
            if in_bucket <= 0:
                return float(ub)
            frac = (target - prev_cum) / in_bucket
            return prev_ub + (float(ub) - prev_ub) * frac
        prev_cum = cum
        if ub is not None:
            prev_ub = float(ub)
    return prev_ub


def histogram_lanes(streams: Dict[int, Dict[str, Any]]
                    ) -> Dict[str, Dict[str, Any]]:
    """Merge every rank's newest histogram snapshot into p50/p99 lanes
    (bucket counts are cumulative AND mergeable: same bucket layout ->
    element-wise sum). Series without bucket counts (pre-ISSUE-13
    streams) are skipped — sum/count alone cannot give percentiles."""
    merged: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for s in streams.values():
        hists = (s.get("snapshot") or {}).get("histograms") or {}
        for name, series in hists.items():
            if not isinstance(series, dict):
                continue
            for labels, h in series.items():
                if not isinstance(h, dict) or "counts" not in h:
                    continue
                key = (name, labels)
                m = merged.get(key)
                if m is None:
                    merged[key] = {"buckets": list(h["buckets"]),
                                   "counts": list(h["counts"]),
                                   "sum": float(h.get("sum", 0.0)),
                                   "count": float(h.get("count", 0)),
                                   "skipped_series": 0}
                elif m["buckets"] == list(h["buckets"]):
                    m["counts"] = [a + b for a, b in
                                   zip(m["counts"], h["counts"])]
                    m["sum"] += float(h.get("sum", 0.0))
                    m["count"] += float(h.get("count", 0))
                else:
                    # mismatched bucket layout (mixed builds): counts
                    # cannot merge — SAY so instead of silently
                    # presenting one rank's view as the fleet's
                    m["skipped_series"] += 1
    out: Dict[str, Dict[str, Any]] = {}
    for (name, labels), m in sorted(merged.items()):
        if m["count"] <= 0:
            continue
        key = f"{name}{{{labels}}}" if labels else name
        out[key] = {
            "count": m["count"],
            "mean": m["sum"] / m["count"],
            "p50": hist_quantile(m["buckets"], m["counts"], 50.0),
            "p99": hist_quantile(m["buckets"], m["counts"], 99.0),
        }
        if m["skipped_series"]:
            out[key]["skipped_series"] = m["skipped_series"]
    return out


def _mean(vals: List[float]) -> float:
    return statistics.fmean(vals) if vals else 0.0


def _median(vals: List[float]) -> float:
    return statistics.median(vals) if vals else 0.0


def summarize(streams: Dict[int, Dict[str, Any]],
              warmup: int = 1) -> Dict[str, Any]:
    """Merge per-rank streams into the triage report dict. The first
    ``warmup`` step records per rank are excluded from the means (step
    0 carries compile+first-dispatch; averaging it in would misname
    compute as the top component of every short run)."""
    report: Dict[str, Any] = {"ranks": sorted(streams), "per_rank": {},
                              "aggregate": {}, "counters": {},
                              "straggler": {}, "warmup_excluded": warmup}
    totals_by_rank: Dict[int, float] = {}
    input_share_by_rank: Dict[int, float] = {}
    all_counters: Dict[str, float] = {}
    for r, s in sorted(streams.items()):
        short = len(s["steps"]) <= warmup
        steps = s["steps"] if short else s["steps"][warmup:]
        if not steps:
            continue
        entry: Dict[str, Any] = {
            "steps": len(steps),
            # a stream shorter than the warmup window can only report
            # its compile-tainted records — flag it rather than hide it
            "warmup_included": short,
            "mean_total_s": _mean([x.get("total_s", 0.0)
                                   for x in steps]),
        }
        for c in COMPONENTS:
            entry[f"mean_{c}"] = _mean([x.get(c, 0.0) for x in steps])
        # exposed-comm %: wire time that EXTENDED the step. Prefer the
        # modeled figure (cost-model overlap accounting stamped into
        # step records as `exposed_comm_s` by cost x rate benches);
        # fall back to the measured collective phase — eager collective
        # dispatch wall time is exposed by construction (the host
        # blocked on it), while compiled-step collectives never show up
        # there at all.
        exp = [x["exposed_comm_s"] for x in steps
               if "exposed_comm_s" in x]
        if exp:
            entry["mean_exposed_comm_s"] = _mean(exp)
            entry["exposed_comm_source"] = "modeled"
        else:
            entry["mean_exposed_comm_s"] = entry["mean_collective_s"]
            entry["exposed_comm_source"] = "collective-wall"
        if entry["mean_total_s"] > 0:
            entry["exposed_comm_pct"] = (
                100.0 * entry["mean_exposed_comm_s"]
                / entry["mean_total_s"])
        # ICI-vs-DCN components of the exposed-comm lane (cost x rate
        # benches stamp exposed_comm_ici_s/exposed_comm_dcn_s from the
        # cost model's per-link-class overlap split): a cross-slice DCN
        # overlap regression is nameable as such instead of collapsing
        # both wire classes into one number
        for cls in ("ici", "dcn"):
            key = f"exposed_comm_{cls}_s"
            vals = [x[key] for x in steps if key in x]
            if vals:
                entry[f"mean_{key}"] = _mean(vals)
                if entry["mean_total_s"] > 0:
                    entry[f"exposed_comm_{cls}_pct"] = (
                        100.0 * entry[f"mean_{key}"]
                        / entry["mean_total_s"])
        toks = [x["tokens"] for x in steps if "tokens" in x]
        secs = [x["total_s"] for x in steps if "tokens" in x]
        if toks and sum(secs) > 0:
            entry["tokens_per_s"] = sum(toks) / sum(secs)
        # modeled step cost (cost x rate benches — the serving engine
        # stamps `modeled_step_s` per decode step): deterministic, so
        # diffing it across runs carries zero sandbox wall-clock noise
        mod = [x["modeled_step_s"] for x in steps
               if "modeled_step_s" in x]
        if mod:
            entry["mean_modeled_step_s"] = _mean(mod)
            mtoks = [x["tokens"] for x in steps
                     if "modeled_step_s" in x and "tokens" in x]
            if mtoks and sum(mod) > 0:
                entry["modeled_tokens_per_s"] = sum(mtoks) / sum(mod)
        # MFU / roofline lane: records stamped with the cost model's
        # (modeled_flops, roofline_s, peak_flops) triple — modeled
        # FLOPs over the roofline time, as a fraction of the chip
        # peak. Deterministic (pure function of program + rate model),
        # so the diff verdict below can gate on it without wall-clock
        # noise.
        mfus = [x["modeled_flops"] / (x["roofline_s"] * x["peak_flops"])
                for x in steps
                if x.get("modeled_flops") and x.get("roofline_s")
                and x.get("peak_flops")]
        if mfus:
            entry["mfu_modeled"] = _mean(mfus)
        # cost x rate economics lane (ISSUE 17): records stamped with
        # (chip_seconds, served_tokens) pairs — modeled chip-seconds
        # spent over tokens delivered. Deterministic like the modeled
        # step, so the diff verdict can gate on COST PER SERVED TOKEN
        # with zero wall-clock noise. The raw sums ride along so the
        # aggregate below can divide fleet chips by fleet tokens
        # instead of averaging per-rank ratios.
        cpairs = [(x["chip_seconds"], x["served_tokens"]) for x in steps
                  if "chip_seconds" in x and "served_tokens" in x]
        if cpairs:
            chip_sum = sum(c for c, _ in cpairs)
            tok_sum = sum(t for _, t in cpairs)
            entry["chip_seconds_total"] = chip_sum
            entry["served_tokens_total"] = tok_sum
            if tok_sum > 0:
                entry["cost_per_served_token"] = chip_sum / tok_sum
        samp = [x["samples"] for x in steps if "samples" in x]
        if samp and entry["mean_total_s"] > 0:
            entry["samples_per_s"] = _mean(samp) / entry["mean_total_s"]
        if any("loss_scale" in x for x in steps):
            entry["last_loss_scale"] = [
                x["loss_scale"] for x in steps
                if "loss_scale" in x][-1]
        report["per_rank"][r] = entry
        totals_by_rank[r] = entry["mean_total_s"]
        if entry["mean_total_s"] > 0:
            input_share_by_rank[r] = (entry["mean_input_wait_s"]
                                      / entry["mean_total_s"])
        for cname in _RELIABILITY_COUNTERS:
            v = _counter_total(s.get("snapshot") or {}, cname)
            if v:
                all_counters[cname] = all_counters.get(cname, 0.0) + v
    report["counters"] = all_counters
    per = report["per_rank"]
    if per:
        agg = {"steps": sum(e["steps"] for e in per.values()),
               "mean_total_s": _mean([e["mean_total_s"]
                                      for e in per.values()])}
        for c in COMPONENTS:
            agg[f"mean_{c}"] = _mean([e[f"mean_{c}"]
                                      for e in per.values()])
        tps = [e["tokens_per_s"] for e in per.values()
               if "tokens_per_s" in e]
        if tps:
            agg["tokens_per_s_total"] = sum(tps)
        pcts = [e["exposed_comm_pct"] for e in per.values()
                if "exposed_comm_pct" in e]
        if pcts:
            agg["exposed_comm_pct"] = _mean(pcts)
            srcs = {e["exposed_comm_source"] for e in per.values()
                    if "exposed_comm_source" in e}
            agg["exposed_comm_source"] = (srcs.pop() if len(srcs) == 1
                                          else "mixed")
        # per-link-class lanes aggregate only when EVERY rank carries
        # them (same gating as the modeled/MFU lanes: a mixed stream
        # would average a cost model against nothing)
        for cls in ("ici", "dcn"):
            cvals = [e.get(f"exposed_comm_{cls}_pct")
                     for e in per.values()]
            if cvals and all(v is not None for v in cvals):
                agg[f"exposed_comm_{cls}_pct"] = _mean(cvals)
        # aggregate modeled lane only when EVERY rank carries it —
        # a mixed stream would average a cost model against nothing
        mods = [e.get("mean_modeled_step_s") for e in per.values()]
        if mods and all(m is not None for m in mods):
            agg["mean_modeled_step_s"] = _mean(mods)
            mtps = [e["modeled_tokens_per_s"] for e in per.values()
                    if "modeled_tokens_per_s" in e]
            if mtps:
                agg["modeled_tokens_per_s_total"] = sum(mtps)
        # MFU lane aggregates only when EVERY rank carries it — one
        # rank's cost model averaged against nothing is not a fleet MFU
        mfu_vals = [e.get("mfu_modeled") for e in per.values()]
        if mfu_vals and all(m is not None for m in mfu_vals):
            agg["mfu_modeled"] = _mean(mfu_vals)
        # cost lane aggregates only when EVERY rank carries it, and as
        # fleet-chips / fleet-tokens (NOT a mean of ratios: a rank that
        # served 10 tokens would weigh as much as one that served 10k)
        cost_vals = [e.get("cost_per_served_token") for e in per.values()]
        if cost_vals and all(c is not None for c in cost_vals):
            fleet_chips = sum(e["chip_seconds_total"]
                              for e in per.values())
            fleet_toks = sum(e["served_tokens_total"]
                             for e in per.values())
            if fleet_toks > 0:
                agg["cost_per_served_token"] = fleet_chips / fleet_toks
                agg["served_tokens_total"] = fleet_toks
                agg["chip_seconds_total"] = fleet_chips
        if agg["mean_total_s"] > 0:
            agg["breakdown_pct"] = {
                _COMPONENT_LABEL[c]: 100.0 * agg[f"mean_{c}"]
                / agg["mean_total_s"] for c in COMPONENTS}
        report["aggregate"] = agg

    # histogram p50/p99 lanes from the cumulative bucket counts the
    # snapshots carry (checkpoint-save seconds, serving TTFT, ...)
    report["histograms"] = histogram_lanes(streams)

    # straggler + slow-input attribution (>= 2 ranks to compare)
    if len(totals_by_rank) >= 2:
        med = _median(list(totals_by_rank.values()))
        report["straggler"]["step_time"] = {
            "median_s": med,
            "suspects": sorted(
                (r for r, t in totals_by_rank.items()
                 if med > 0 and t > _STRAGGLER_K * med),
                key=lambda r: -totals_by_rank[r])}
        med_share = _median(list(input_share_by_rank.values()))
        report["straggler"]["input_wait"] = {
            "median_share": med_share,
            "suspects": sorted(
                (r for r, sh in input_share_by_rank.items()
                 if sh > max(_STRAGGLER_K * med_share, 0.05)),
                key=lambda r: -input_share_by_rank[r])}
    return report


def diff(base: Dict[str, Any], new: Dict[str, Any],
         threshold_pct: float = 10.0) -> Dict[str, Any]:
    """Compare two summarize() reports: per-component mean-step deltas,
    the top regressed component, and the regression verdict."""
    a = base.get("aggregate") or {}
    b = new.get("aggregate") or {}
    comps: Dict[str, Dict[str, float]] = {}
    top: Optional[str] = None
    top_delta = 0.0
    for c in COMPONENTS:
        va, vb = a.get(f"mean_{c}", 0.0), b.get(f"mean_{c}", 0.0)
        delta = vb - va
        # None = "new component" (base was 0): inf would serialize as
        # a bare Infinity literal and break --json consumers
        comps[_COMPONENT_LABEL[c]] = {
            "base_s": va, "new_s": vb, "delta_s": delta,
            "delta_pct": (100.0 * delta / va) if va > 0 else
            (None if delta > 0 else 0.0)}
        if delta > top_delta:
            top_delta = delta
            top = _COMPONENT_LABEL[c]
    ta, tb = a.get("mean_total_s", 0.0), b.get("mean_total_s", 0.0)
    total_delta_pct = (100.0 * (tb - ta) / ta) if ta > 0 else 0.0
    out = {
        "components": comps,
        "top_regressed": top,
        "base_total_s": ta, "new_total_s": tb,
        "total_delta_pct": total_delta_pct,
        "threshold_pct": threshold_pct,
        "regressed": total_delta_pct > threshold_pct,
        "verdict_source": "wall",
    }
    # when BOTH streams carry the modeled-step lane (cost x rate
    # benches, the serving engine), the regression verdict uses the
    # MODELED delta: it is a pure function of (program, rate model),
    # so CI diffs of identical code are exactly 0% instead of sandbox
    # wall-clock noise tripping the threshold
    ma = a.get("mean_modeled_step_s")
    mb = b.get("mean_modeled_step_s")
    if ma is not None or mb is not None:
        comparable = ma is not None and mb is not None
        mdelta = (100.0 * (mb - ma) / ma) if comparable and ma > 0 \
            else None
        out["modeled_step"] = {
            "base_s": ma, "new_s": mb, "delta_pct": mdelta,
            "comparable": comparable,
            "base_tokens_per_s": a.get("modeled_tokens_per_s_total"),
            "new_tokens_per_s": b.get("modeled_tokens_per_s_total"),
        }
        if mdelta is not None:
            out["total_delta_pct"] = mdelta
            out["regressed"] = mdelta > threshold_pct
            out["verdict_source"] = "modeled"
    # MFU / roofline delta: deterministic like the modeled step, so a
    # drop IS a program-shape regression (a remat policy that stopped
    # fitting, a fast path that fell back) — comparable only when both
    # streams carry the lane, and then it FAILS the gate exactly like
    # a modeled-step regression does
    fa = a.get("mfu_modeled")
    fb = b.get("mfu_modeled")
    if fa is not None or fb is not None:
        comparable = fa is not None and fb is not None
        drop_pct = (100.0 * (fa - fb) / fa) if comparable and fa > 0 \
            else None
        out["mfu_modeled"] = {
            "base": fa, "new": fb, "drop_pct": drop_pct,
            "comparable": comparable,
            "regressed": bool(drop_pct is not None
                              and drop_pct > threshold_pct)}
        if out["mfu_modeled"]["regressed"] and not out["regressed"]:
            out["regressed"] = True
            out["verdict_source"] = "mfu"
            out["total_delta_pct"] = drop_pct
    # cost-per-served-token delta (ISSUE 17): deterministic economics —
    # a RISE is a regression (more chip-seconds bought per token
    # delivered). Comparable only when both streams carry the lane, and
    # then it fails the gate exactly like a modeled-step regression.
    ca = a.get("cost_per_served_token")
    cb = b.get("cost_per_served_token")
    if ca is not None or cb is not None:
        comparable = ca is not None and cb is not None
        rise_pct = (100.0 * (cb - ca) / ca) if comparable and ca > 0 \
            else None
        out["cost_per_served_token"] = {
            "base": ca, "new": cb, "delta_pct": rise_pct,
            "comparable": comparable,
            "base_served_tokens": a.get("served_tokens_total"),
            "new_served_tokens": b.get("served_tokens_total"),
            "regressed": bool(rise_pct is not None
                              and rise_pct > threshold_pct)}
        if out["cost_per_served_token"]["regressed"] \
                and not out["regressed"]:
            out["regressed"] = True
            out["verdict_source"] = "cost"
            out["total_delta_pct"] = rise_pct
    # exposed-comm % delta: an overlap regression (a bucket that
    # stopped hiding under backward, a prefetch that went eager) shows
    # up HERE even when total step time moved for other reasons too.
    # Only COMPARABLE when both sides measured it the same way — a
    # modeled stream diffed against a collective-wall fallback stream
    # is a metric-source change, not an overlap change.
    if "exposed_comm_pct" in a or "exposed_comm_pct" in b:
        sa = a.get("exposed_comm_source")
        sb = b.get("exposed_comm_source")
        out["exposed_comm_pct"] = {
            "base": a.get("exposed_comm_pct", 0.0),
            "new": b.get("exposed_comm_pct", 0.0),
            "base_source": sa, "new_source": sb,
            "comparable": sa == sb and sa is not None
            and sa != "mixed"}
        # per-link-class deltas ride along when both streams carry the
        # split, so the OVERLAP REGRESSION marker can name WHICH wire
        # class stopped hiding (a grown DCN share is a cross-slice
        # hierarchy/bucketing problem; a grown ICI share is in-slice)
        for cls in ("ici", "dcn"):
            ka = a.get(f"exposed_comm_{cls}_pct")
            kb = b.get(f"exposed_comm_{cls}_pct")
            if ka is not None and kb is not None:
                out["exposed_comm_pct"][cls] = {"base": ka, "new": kb}
    # counter deltas that explain a regression (retries eat wall time)
    cdeltas = {}
    for cname in _RELIABILITY_COUNTERS:
        va = (base.get("counters") or {}).get(cname, 0.0)
        vb = (new.get("counters") or {}).get(cname, 0.0)
        if vb != va:
            cdeltas[cname] = {"base": va, "new": vb}
    out["counter_deltas"] = cdeltas
    return out


# ---------------------------------------------------------------- report
def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.3f}s"
    return f"{v * 1e3:.3f}ms"


def format_summary(report: Dict[str, Any], directory: str) -> str:
    L: List[str] = []
    ranks = report["ranks"]
    L.append(f"perf_doctor: merged {len(ranks)} rank stream(s) from "
             f"{directory}")
    if not report["per_rank"]:
        L.append("  no step records found — is PADDLE_METRICS_DIR set "
                 "on the workers (and did the run call metrics.flush() "
                 "or exit cleanly)?")
        return "\n".join(L)
    agg = report["aggregate"]
    L.append(f"  steps: {agg['steps']} (first {report['warmup_excluded']}"
             f" per rank excluded as warmup)   mean step: "
             f"{_fmt_s(agg['mean_total_s'])}")
    if "breakdown_pct" in agg:
        parts = "  ".join(
            f"{name} {_fmt_s(agg['mean_' + c])} "
            f"({agg['breakdown_pct'][name]:.1f}%)"
            for c, name in _COMPONENT_LABEL.items())
        L.append(f"  breakdown: {parts}")
    if "tokens_per_s_total" in agg:
        L.append(f"  throughput: {agg['tokens_per_s_total']:,.0f} "
                 f"tokens/s aggregate")
    if "exposed_comm_pct" in agg:
        split = ""
        if ("exposed_comm_ici_pct" in agg
                or "exposed_comm_dcn_pct" in agg):
            split = (f" [ici {agg.get('exposed_comm_ici_pct', 0.0):.1f}%"
                     f" + dcn "
                     f"{agg.get('exposed_comm_dcn_pct', 0.0):.1f}%]")
        L.append(f"  exposed-comm: {agg['exposed_comm_pct']:.1f}% of "
                 f"step (wire time NOT hidden under compute){split}")
    if "mfu_modeled" in agg:
        L.append(f"  MFU (modeled): {100.0 * agg['mfu_modeled']:.1f}% "
                 f"of chip peak over the roofline step time "
                 f"(deterministic cost model)")
    if "cost_per_served_token" in agg:
        L.append(f"  cost: {agg['cost_per_served_token']:.3e} "
                 f"chip-seconds per served token "
                 f"({agg['chip_seconds_total']:,.0f} chip-s over "
                 f"{agg['served_tokens_total']:,.0f} tokens, modeled)")
    for r, e in sorted(report["per_rank"].items()):
        extra = ""
        if "tokens_per_s" in e:
            extra = f"  {e['tokens_per_s']:,.0f} tok/s"
        if "exposed_comm_pct" in e:
            extra += (f"  exposed-comm {e['exposed_comm_pct']:.1f}% "
                      f"[{e['exposed_comm_source']}]")
            if "exposed_comm_dcn_pct" in e:
                extra += (f" (ici "
                          f"{e.get('exposed_comm_ici_pct', 0.0):.1f}%"
                          f"/dcn {e['exposed_comm_dcn_pct']:.1f}%)")
        if "mfu_modeled" in e:
            extra += f"  MFU {100.0 * e['mfu_modeled']:.1f}%"
        if "cost_per_served_token" in e:
            extra += (f"  cost {e['cost_per_served_token']:.3e} "
                      f"chip-s/token")
        if e.get("warmup_included"):
            extra += "  [WARMUP INCLUDED: stream shorter than warmup]"
        L.append(f"  rank {r}: {e['steps']} steps, mean "
                 f"{_fmt_s(e['mean_total_s'])} (input "
                 f"{_fmt_s(e['mean_input_wait_s'])}, compute "
                 f"{_fmt_s(e['mean_compute_s'])}, collective "
                 f"{_fmt_s(e['mean_collective_s'])}, host "
                 f"{_fmt_s(e['mean_host_s'])}){extra}")
    if report["counters"]:
        L.append("RELIABILITY COUNTERS")
        for name, v in sorted(report["counters"].items()):
            L.append(f"  {name}: {v:g}")
    if report.get("histograms"):
        L.append("HISTOGRAMS (p50/p99 from cumulative bucket counts)")
        for name, h in report["histograms"].items():
            p50 = _fmt_s(h["p50"]) if h["p50"] is not None else "n/a"
            p99 = _fmt_s(h["p99"]) if h["p99"] is not None else "n/a"
            tag = (f"  [INCOMPLETE: {h['skipped_series']} series "
                   f"with a different bucket layout skipped]"
                   if h.get("skipped_series") else "")
            L.append(f"  {name}: n={h['count']:g} "
                     f"mean={_fmt_s(h['mean'])} p50~{p50} p99~{p99}"
                     f"{tag}")
    s = report.get("straggler", {})
    st = s.get("step_time", {})
    si = s.get("input_wait", {})
    if st.get("suspects"):
        L.append(f"STRAGGLER: rank(s) "
                 f"{','.join(map(str, st['suspects']))} mean step time "
                 f"> {_STRAGGLER_K:g}x the {_fmt_s(st['median_s'])} "
                 f"median")
    if si.get("suspects"):
        L.append(f"SLOW INPUT: rank(s) "
                 f"{','.join(map(str, si['suspects']))} input-wait "
                 f"share is an outlier (median share "
                 f"{si['median_share']:.1%}) — a data-pipeline "
                 f"problem, not a chip problem")
    fl = report.get("flight") or {}
    if fl.get("reasons") or fl.get("event_counts"):
        L.append("FLIGHT-RECORDER JOIN")
        for r, reason in sorted(fl.get("reasons", {}).items()):
            L.append(f"  rank {r} dumped for {reason!r}")
        interesting = {k: v for k, v in fl.get("event_counts",
                                               {}).items()
                       if k in ("step_retry", "worker_respawn", "chaos",
                                "collective_timeout", "watchdog_overrun",
                                "scale_update", "compile")}
        if interesting:
            L.append("  events: " + "  ".join(
                f"{k}={v}" for k, v in sorted(interesting.items())))
    tr = report.get("trace") or {}
    if tr:
        L.append("MERGED-TRACE JOIN (ProfileStep spans)")
        for lane, e in sorted(tr.items()):
            L.append(f"  {lane}: {e['steps']} steps, mean "
                     f"{_fmt_s(e['mean_step_s'])}")
    return "\n".join(L)


def format_diff(d: Dict[str, Any]) -> str:
    L: List[str] = []
    L.append(f"perf_doctor diff: mean step {_fmt_s(d['base_total_s'])} "
             f"-> {_fmt_s(d['new_total_s'])} "
             f"({d['total_delta_pct']:+.1f}%)")
    for name, c in d["components"].items():
        pct = c["delta_pct"]
        pct_s = f"{pct:+.1f}%" if pct is not None else "new"
        L.append(f"  {name:<11} {_fmt_s(c['base_s'])} -> "
                 f"{_fmt_s(c['new_s'])} ({pct_s})")
    if d["top_regressed"]:
        L.append(f"TOP REGRESSED COMPONENT: {d['top_regressed']} "
                 f"(+{_fmt_s(d['components'][d['top_regressed']]['delta_s'])}"
                 f" per step)")
    else:
        L.append("no component regressed")
    ec = d.get("exposed_comm_pct")
    if ec:
        if ec.get("comparable"):
            tag = ""
            if ec["new"] > ec["base"] + 1.0:
                # name the wire class that stopped hiding when the
                # split lanes are present — a DCN regression is a
                # cross-slice hierarchy/bucketing problem, an ICI one
                # is in-slice overlap
                cls_tags = [cls.upper() for cls in ("dcn", "ici")
                            if ec.get(cls)
                            and ec[cls]["new"] > ec[cls]["base"] + 1.0]
                tag = (f"  ({' + '.join(cls_tags)} OVERLAP REGRESSION)"
                       if cls_tags else "  (OVERLAP REGRESSION)")
        else:
            tag = (f"  [incomparable: {ec['base_source']} vs "
                   f"{ec['new_source']}]")
        L.append(f"  exposed-comm: {ec['base']:.1f}% -> "
                 f"{ec['new']:.1f}% of step{tag}")
        for cls in ("ici", "dcn"):
            if ec.get(cls):
                L.append(f"    {cls}: {ec[cls]['base']:.1f}% -> "
                         f"{ec[cls]['new']:.1f}%")
    mf = d.get("mfu_modeled")
    if mf:
        if mf.get("comparable"):
            tag = "  (MFU REGRESSION)" if mf["regressed"] else ""
            L.append(f"  MFU (modeled): {100.0 * mf['base']:.1f}% -> "
                     f"{100.0 * mf['new']:.1f}% of peak{tag}")
        else:
            L.append("  MFU (modeled): [incomparable: only one stream "
                     "carries the roofline lane]")
    co = d.get("cost_per_served_token")
    if co:
        if co.get("comparable"):
            tag = "  (COST REGRESSION)" if co["regressed"] else ""
            L.append(f"  cost/served-token: {co['base']:.3e} -> "
                     f"{co['new']:.3e} chip-s "
                     f"({co['delta_pct']:+.2f}%, deterministic){tag}")
        else:
            L.append("  cost/served-token: [incomparable: only one "
                     "stream carries the cost lane]")
    ms = d.get("modeled_step")
    if ms:
        if ms.get("comparable"):
            L.append(f"  modeled step: {_fmt_s(ms['base_s'])} -> "
                     f"{_fmt_s(ms['new_s'])} "
                     f"({ms['delta_pct']:+.2f}%, deterministic)")
            if ms.get("base_tokens_per_s") and ms.get("new_tokens_per_s"):
                L.append(f"  modeled tokens/s: "
                         f"{ms['base_tokens_per_s']:,.0f} -> "
                         f"{ms['new_tokens_per_s']:,.0f}")
        else:
            L.append("  modeled step: [incomparable: only one stream "
                     "carries modeled_step_s]")
    for name, c in sorted(d.get("counter_deltas", {}).items()):
        L.append(f"  counter {name}: {c['base']:g} -> {c['new']:g}")
    src = d.get("verdict_source", "wall")
    L.append(f"verdict: "
             + (f"REGRESSION ({src} {d['total_delta_pct']:+.1f}% > "
                f"{d['threshold_pct']:g}% threshold)" if d["regressed"]
                else f"ok ({src} {d['total_delta_pct']:+.1f}% within "
                     f"{d['threshold_pct']:g}%)"))
    return "\n".join(L)


# ---------------------------------------------------------------- CLI
def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "diff":
        return _main_diff(argv[1:])
    if argv and argv[0] == "summary":
        argv = argv[1:]
    p = argparse.ArgumentParser(
        prog="paddle2_tpu.tools.perf_doctor",
        description="step-time breakdown, throughput, and reliability-"
                    "counter triage from the always-on metrics plane "
                    "(see also: the `diff` subcommand)")
    p.add_argument("metrics_dir", nargs="?",
                   default=os.environ.get("PADDLE_METRICS_DIR"),
                   help="directory holding metrics_rank_N.jsonl "
                        "(default: $PADDLE_METRICS_DIR)")
    p.add_argument("--flight-dir",
                   default=os.environ.get("PADDLE_FLIGHT_DIR"),
                   help="flight-recorder dump dir to join "
                        "(default: $PADDLE_FLIGHT_DIR)")
    p.add_argument("--trace", default=None,
                   help="merged chrome trace (profiler.merge_traces "
                        "output) to cross-check step spans against")
    p.add_argument("--warmup", type=int, default=1,
                   help="per-rank step records excluded from means "
                        "(default 1: the compile step)")
    p.add_argument("--json", action="store_true",
                   help="emit the structured report as JSON")
    args = p.parse_args(argv)
    if not args.metrics_dir:
        p.error("no metrics dir: pass one or set PADDLE_METRICS_DIR")
    streams = load_streams(args.metrics_dir)
    report = summarize(streams, warmup=max(0, args.warmup))
    report["flight"] = load_flight_counters(args.flight_dir)
    report["trace"] = load_trace_steps(args.trace)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(format_summary(report, args.metrics_dir))
    return 0 if report["per_rank"] else 2


def _main_diff(argv: List[str]) -> int:
    p = argparse.ArgumentParser(
        prog="paddle2_tpu.tools.perf_doctor diff",
        description="diff two metrics streams; exits "
                    f"{REGRESSION_EXIT} on regression (CI gate)")
    p.add_argument("base_dir", help="baseline metrics dir (or file)")
    p.add_argument("new_dir", help="candidate metrics dir (or file)")
    p.add_argument("--threshold", type=float, default=10.0,
                   help="total mean-step regression %% that fails the "
                        "gate (default 10)")
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    base = summarize(load_streams(args.base_dir),
                     warmup=max(0, args.warmup))
    new = summarize(load_streams(args.new_dir),
                    warmup=max(0, args.warmup))
    if not base["per_rank"] or not new["per_rank"]:
        print("perf_doctor diff: one side has no step records",
              file=sys.stderr)
        return 2
    d = diff(base, new, threshold_pct=args.threshold)
    if args.json:
        print(json.dumps(d, indent=2, default=str))
    else:
        print(format_diff(d))
    return REGRESSION_EXIT if d["regressed"] else 0


if __name__ == "__main__":
    sys.exit(main())
