"""serve_doctor: exact tail-latency attribution for the serving fleet.

The serving sibling of ``perf_doctor`` (which answers "where did the
STEP time go" for training): this CLI answers "which lifecycle stage
owns the TAIL" for requests, from the per-rank span streams the
request-tracing plane writes (``trace_rank_N.jsonl`` under
``PADDLE_TRACE_DIR``)::

    python -m paddle2_tpu.tools.serve_doctor /path/to/trace_dir
    python -m paddle2_tpu.tools.serve_doctor diff BASE_DIR CAND_DIR
    python -m paddle2_tpu.tools.serve_doctor --json trace_dir

Three triage answers:

1. **Where does each request's latency go?** Every finished request is
   decomposed into ``queue_wait + prefill + decode_compute +
   eviction_stall + failover_stall + swap_stall + host`` summing
   EXACTLY to its e2e latency (integer-picosecond accounting, host =
   residual — the step-window rule applied per request). Violations
   are a report section, not a silent skip.
2. **Who owns the tail?** The p99-vs-p50 gap is attributed by
   comparing the decomposition of the request AT p99 (nearest-rank)
   against the one at p50: the component with the largest positive
   delta owns the gap. An injected overload names ``queue_wait``; a
   dropped-decode chaos fault names ``decode_compute`` — and the CHAOS
   section lists exactly which trace ids each injected fault touched
   (the flight ring's chaos spans carry ``tids``).
3. **What regressed?** ``diff BASE CAND`` compares per-request
   component means and the e2e p50/p99, names the top regressed
   component, and exits ``REGRESSION_EXIT`` (4) when the p99 (or
   mean) e2e regression passes the threshold. Traces from the
   virtual-clock simulators are bit-deterministic, so identical code
   diffs at EXACTLY 0%% — the CI-gating primitive.

``--metrics-dir`` joins the metrics plane's SLO ledger
(``serving_slo_*`` counters + burn-rate gauge) into the report, so
one view carries both "who is slow" and "are we burning budget".

Stdlib-only analysis (the flight_doctor/perf_doctor posture); span
parsing and decomposition are delegated to
``observability.tracing`` — ONE reader owns the span format.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

REGRESSION_EXIT = 4

_COMPONENT_LABEL = {
    "queue_wait_s": "queue-wait",
    "prefill_s": "prefill",
    "decode_compute_s": "decode-compute",
    "eviction_stall_s": "eviction-stall",
    "failover_stall_s": "failover-stall",
    "swap_stall_s": "swap-stall",
    "spill_fetch_s": "spill-fetch",
    "migration_stall_s": "migration-stall",
    "host_s": "host",
}

# chaos span shapes the attribution section knows how to blame
_CHAOS_EVENTS = ("decode_step_dropped", "table_corrupt", "engine_failed")


def _components():
    from ..observability import tracing
    return tracing.COMPONENTS


def _mean(vals: List[float]) -> float:
    return sum(vals) / len(vals) if vals else 0.0


def _rank_at(sorted_vals: List, q: float) -> int:
    """Nearest-rank index for quantile ``q`` in [0, 100] — integer
    arithmetic, deterministic, no interpolation."""
    n = len(sorted_vals)
    if n == 0:
        return 0
    return min(n - 1, max(0, -(-int(q * n) // 100) - 1))


# ---------------------------------------------------------------- analysis
def summarize(records: List[Dict[str, Any]],
              metrics_dir: Optional[str] = None) -> Dict[str, Any]:
    """Span records (``tracing.load_trace_dir`` output) -> the triage
    report dict."""
    from ..observability import tracing
    decomps = tracing.decompose(records)
    comps = _components()
    finished = {t: c for t, c in decomps.items() if c["finished"]}
    shed = [t for t, c in decomps.items() if c.get("shed")]
    unfinished = [t for t, c in decomps.items()
                  if not c["finished"] and not c.get("shed")]
    violations = [t for t, c in finished.items() if not c["exact"]]

    report: Dict[str, Any] = {
        "requests": len(decomps), "finished": len(finished),
        "shed": len(shed), "unfinished": len(unfinished),
        "exactness": {"checked": len(finished),
                      "violations": sorted(violations, key=str)},
        "decompositions": decomps,
    }
    if finished:
        by_e2e = sorted(finished, key=lambda t: (finished[t]["e2e_ps"],
                                                 str(t)))
        e2e = [finished[t]["e2e_s"] for t in by_e2e]
        stats: Dict[str, Any] = {
            "e2e": {"mean_s": _mean(e2e),
                    "p50_s": e2e[_rank_at(e2e, 50)],
                    "p99_s": e2e[_rank_at(e2e, 99)]}}
        for c in comps:
            vals = [finished[t][c] for t in by_e2e]
            stats[c] = {"mean_s": _mean(vals),
                        "share_pct": (100.0 * _mean(vals)
                                      / stats["e2e"]["mean_s"]
                                      if stats["e2e"]["mean_s"] else 0.0)}
        ttfts = sorted(c["ttft_s"] for c in finished.values()
                       if c.get("ttft_s") is not None)
        if ttfts:
            stats["ttft"] = {"p50_s": ttfts[_rank_at(ttfts, 50)],
                             "p99_s": ttfts[_rank_at(ttfts, 99)]}
        report["stats"] = stats
        # tail attribution: the request AT p99 vs the one AT p50
        t50 = by_e2e[_rank_at(by_e2e, 50)]
        t99 = by_e2e[_rank_at(by_e2e, 99)]
        gap = {c: finished[t99][c] - finished[t50][c] for c in comps}
        owner = max(comps, key=lambda c: gap[c])
        report["tail"] = {
            "p50_tid": t50, "p99_tid": t99,
            "gap_s": finished[t99]["e2e_s"] - finished[t50]["e2e_s"],
            "component_gaps_s": gap,
            "owner": owner,
            "owner_label": _COMPONENT_LABEL[owner],
            "owner_gap_s": gap[owner],
        }
        report["counters"] = {
            k: sum(c[k] for c in finished.values())
            for k in ("evictions", "retries", "failovers",
                      "corruptions", "swaps", "spill_fetches",
                      "migrations")}
    # chaos attribution: which injected fault touched which requests
    chaos: Dict[str, List] = {}
    for rec in records:
        name = rec.get("event")
        if name in _CHAOS_EVENTS or "chaos" in rec:
            key = rec.get("chaos") or name
            tids = rec.get("tids") or (
                [rec["tid"]] if "tid" in rec else [])
            chaos.setdefault(key, []).extend(tids)
    if chaos:
        report["chaos"] = {k: sorted(set(v), key=str)
                           for k, v in sorted(chaos.items())}
    if metrics_dir:
        report["slo"] = load_slo(metrics_dir)
        report["throughput"] = load_throughput(metrics_dir)
    return report


def load_slo(metrics_dir: str) -> Dict[str, Any]:
    """Join the metrics plane's SLO ledger: good/bad totals,
    per-dimension check verdicts, and the burn-rate gauge, read from
    the newest metrics snapshot of every rank stream."""
    from . import perf_doctor
    streams = perf_doctor.load_streams(metrics_dir)
    out: Dict[str, Any] = {"good": 0.0, "bad": 0.0, "checks": {},
                           "burn_rate": None}
    for s in streams.values():
        snap = s.get("snapshot") or {}
        out["good"] += perf_doctor._counter_total(
            snap, "serving_slo_good_total")
        out["bad"] += perf_doctor._counter_total(
            snap, "serving_slo_bad_total")
        checks = (snap.get("counters") or {}).get(
            "serving_slo_checks_total") or {}
        for labels, v in checks.items():
            out["checks"][labels] = out["checks"].get(labels, 0.0) + v
        gauges = (snap.get("gauges") or {}).get(
            "serving_slo_burn_rate") or {}
        for v in gauges.values():
            # the WORST rank's burn rate — summed good/bad totals next
            # to one arbitrary rank's gauge would be inconsistent
            if out["burn_rate"] is None or v > out["burn_rate"]:
                out["burn_rate"] = v
    total = out["good"] + out["bad"]
    out["attainment"] = out["good"] / total if total else None
    return out


def load_throughput(metrics_dir: str) -> Dict[str, Any]:
    """Join the serving-throughput economics (ISSUE 14) from the
    metrics snapshots: prefix-cache hits/misses (+ the shared-KV-bytes
    gauge) and the speculative-decoding accepted/rejected ledger with
    its derived acceptance rate — the one number an acceptance-rate
    regression moves first."""
    from . import perf_doctor
    streams = perf_doctor.load_streams(metrics_dir)
    out: Dict[str, Any] = {"prefix_hits": 0.0, "prefix_misses": 0.0,
                           "shared_kv_bytes": None,
                           "spec_accepted": 0.0, "spec_rejected": 0.0}
    for s in streams.values():
        snap = s.get("snapshot") or {}
        out["prefix_hits"] += perf_doctor._counter_total(
            snap, "serving_prefix_hits_total")
        out["prefix_misses"] += perf_doctor._counter_total(
            snap, "serving_prefix_misses_total")
        out["spec_accepted"] += perf_doctor._counter_total(
            snap, "serving_spec_accepted_total")
        out["spec_rejected"] += perf_doctor._counter_total(
            snap, "serving_spec_rejected_total")
        gauges = (snap.get("gauges") or {}).get(
            "serving_shared_kv_bytes") or {}
        for v in gauges.values():
            out["shared_kv_bytes"] = (v if out["shared_kv_bytes"] is None
                                      else out["shared_kv_bytes"] + v)
    lookups = out["prefix_hits"] + out["prefix_misses"]
    out["prefix_hit_rate"] = (out["prefix_hits"] / lookups
                              if lookups else None)
    proposed = out["spec_accepted"] + out["spec_rejected"]
    out["spec_acceptance"] = (out["spec_accepted"] / proposed
                              if proposed else None)
    return out


def diff(base: Dict[str, Any], new: Dict[str, Any],
         threshold_pct: float = 10.0) -> Dict[str, Any]:
    """Compare two summarize() reports: per-component mean-per-request
    deltas, e2e p50/p99 deltas, the top regressed component, and the
    regression verdict (p99-first — tails are the product here)."""
    comps = _components()
    a, b = base.get("stats") or {}, new.get("stats") or {}
    out: Dict[str, Any] = {"components": {}, "threshold_pct":
                           threshold_pct}
    top, top_delta = None, 0.0
    for c in comps:
        va = (a.get(c) or {}).get("mean_s", 0.0)
        vb = (b.get(c) or {}).get("mean_s", 0.0)
        delta = vb - va
        out["components"][_COMPONENT_LABEL[c]] = {
            "base_s": va, "new_s": vb, "delta_s": delta,
            "delta_pct": (100.0 * delta / va) if va > 0
            else (None if delta > 0 else 0.0)}
        if delta > top_delta:
            top, top_delta = _COMPONENT_LABEL[c], delta
    out["top_regressed"] = top
    for lane in ("p50_s", "p99_s", "mean_s"):
        va = (a.get("e2e") or {}).get(lane, 0.0)
        vb = (b.get("e2e") or {}).get(lane, 0.0)
        out[f"e2e_{lane[:-2]}"] = {
            "base_s": va, "new_s": vb,
            "delta_pct": (100.0 * (vb - va) / va) if va > 0 else 0.0}
    p99 = out["e2e_p99"]["delta_pct"]
    mean = out["e2e_mean"]["delta_pct"]
    out["regressed"] = (p99 > threshold_pct or mean > threshold_pct)
    out["verdict_source"] = "p99" if p99 >= mean else "mean"
    out["total_delta_pct"] = max(p99, mean)
    # counter deltas (retries eat steps, failovers eat re-prefills)
    cdeltas = {}
    for k in ("evictions", "retries", "failovers", "corruptions",
              "swaps", "spill_fetches", "migrations"):
        va = (base.get("counters") or {}).get(k, 0)
        vb = (new.get("counters") or {}).get(k, 0)
        if va != vb:
            cdeltas[k] = {"base": va, "new": vb}
    out["counter_deltas"] = cdeltas
    out["exactness_ok"] = (
        not (base.get("exactness") or {}).get("violations")
        and not (new.get("exactness") or {}).get("violations"))
    return out


# ---------------------------------------------------------------- report
def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "n/a"
    if v >= 1.0:
        return f"{v:.4f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.3f}ms"
    return f"{v * 1e6:.1f}us"


def format_summary(report: Dict[str, Any], directory: str) -> str:
    L: List[str] = []
    L.append(f"serve_doctor: {report['requests']} request trace(s) "
             f"from {directory} — {report['finished']} finished, "
             f"{report['shed']} shed, {report['unfinished']} unfinished")
    if not report["finished"]:
        L.append("  no finished requests — is PADDLE_TRACE_DIR set on "
                 "the serving process (and did it flush)?")
        return "\n".join(L)
    ex = report["exactness"]
    if ex["violations"]:
        L.append(f"DECOMPOSITION VIOLATIONS: {len(ex['violations'])}/"
                 f"{ex['checked']} finished request(s) do NOT sum "
                 f"exactly: tids {ex['violations']} — the span "
                 f"bookkeeping (not the arithmetic) is broken")
    else:
        L.append(f"  decomposition exact on all {ex['checked']} "
                 f"finished requests (components + host == e2e, "
                 f"integer-ps)")
    st = report["stats"]
    e2e = st["e2e"]
    L.append(f"  e2e: mean {_fmt_s(e2e['mean_s'])}  p50 "
             f"{_fmt_s(e2e['p50_s'])}  p99 {_fmt_s(e2e['p99_s'])}")
    if "ttft" in st:
        L.append(f"  ttft: p50 {_fmt_s(st['ttft']['p50_s'])}  p99 "
                 f"{_fmt_s(st['ttft']['p99_s'])}")
    parts = "  ".join(
        f"{_COMPONENT_LABEL[c]} {_fmt_s(st[c]['mean_s'])} "
        f"({st[c]['share_pct']:.1f}%)" for c in _components())
    L.append(f"  mean breakdown: {parts}")
    tail = report["tail"]
    L.append(f"TAIL (p99-p50 gap {_fmt_s(tail['gap_s'])}, request "
             f"{tail['p99_tid']} vs {tail['p50_tid']}): owned by "
             f"{tail['owner_label']} "
             f"(+{_fmt_s(tail['owner_gap_s'])})")
    gaps = tail["component_gaps_s"]
    L.append("  gap by component: " + "  ".join(
        f"{_COMPONENT_LABEL[c]} {gaps[c] * 1e6:+.1f}us"
        for c in _components()))
    cnt = report.get("counters") or {}
    if any(cnt.values()):
        L.append("  lifecycle counters: " + "  ".join(
            f"{k}={v}" for k, v in sorted(cnt.items()) if v))
    ch = report.get("chaos")
    if ch:
        L.append("CHAOS ATTRIBUTION (injected faults -> requests)")
        for fault, tids in ch.items():
            L.append(f"  {fault}: tids {tids}")
    thr = report.get("throughput")
    if thr and (thr["prefix_hits"] or thr["prefix_misses"]
                or thr["spec_accepted"] or thr["spec_rejected"]):
        L.append("THROUGHPUT (prefix cache / speculation)")
        if thr["prefix_hits"] or thr["prefix_misses"]:
            hr = thr.get("prefix_hit_rate")
            shared = thr.get("shared_kv_bytes")
            L.append(
                f"  prefix cache: {thr['prefix_hits']:g} hits / "
                f"{thr['prefix_misses']:g} misses"
                + (f" ({hr:.1%} hit rate)" if hr is not None else "")
                + (f", {shared:,.0f} B KV shared"
                   if shared else ""))
        if thr["spec_accepted"] or thr["spec_rejected"]:
            acc = thr.get("spec_acceptance")
            L.append(
                f"  speculation: {thr['spec_accepted']:g} accepted / "
                f"{thr['spec_rejected']:g} rejected drafts"
                + (f" ({acc:.1%} acceptance rate)"
                   if acc is not None else ""))
    slo = report.get("slo")
    if slo and (slo["good"] or slo["bad"]):
        att = slo.get("attainment")
        L.append(f"SLO: {slo['good']:g} good / {slo['bad']:g} bad "
                 f"({att:.1%} attainment)" if att is not None
                 else f"SLO: {slo['good']:g} good / {slo['bad']:g} bad")
        for labels, v in sorted((slo.get("checks") or {}).items()):
            L.append(f"  checks[{labels}]: {v:g}")
        if slo.get("burn_rate") is not None:
            br = slo["burn_rate"]
            tag = "  (BUDGET BURNING)" if br > 1.0 else ""
            L.append(f"  burn rate: {br:.2f}x sustainable{tag}")
    return "\n".join(L)


def format_diff(d: Dict[str, Any]) -> str:
    L: List[str] = []
    p50, p99, mean = d["e2e_p50"], d["e2e_p99"], d["e2e_mean"]
    L.append(f"serve_doctor diff: e2e mean "
             f"{_fmt_s(mean['base_s'])} -> {_fmt_s(mean['new_s'])} "
             f"({mean['delta_pct']:+.2f}%)  p50 "
             f"{_fmt_s(p50['base_s'])} -> {_fmt_s(p50['new_s'])} "
             f"({p50['delta_pct']:+.2f}%)  p99 "
             f"{_fmt_s(p99['base_s'])} -> {_fmt_s(p99['new_s'])} "
             f"({p99['delta_pct']:+.2f}%)")
    for name, c in d["components"].items():
        pct = c["delta_pct"]
        pct_s = f"{pct:+.2f}%" if pct is not None else "new"
        L.append(f"  {name:<14} {_fmt_s(c['base_s'])} -> "
                 f"{_fmt_s(c['new_s'])} ({pct_s})")
    if d["top_regressed"]:
        L.append(f"TOP REGRESSED COMPONENT: {d['top_regressed']} "
                 f"(+{_fmt_s(d['components'][d['top_regressed']]['delta_s'])}"
                 f" per request)")
    else:
        L.append("no component regressed")
    for name, c in sorted(d.get("counter_deltas", {}).items()):
        L.append(f"  counter {name}: {c['base']:g} -> {c['new']:g}")
    if not d.get("exactness_ok", True):
        L.append("  WARNING: one side has decomposition violations")
    src = d["verdict_source"]
    L.append("verdict: "
             + (f"REGRESSION ({src} {d['total_delta_pct']:+.2f}% > "
                f"{d['threshold_pct']:g}% threshold)" if d["regressed"]
                else f"ok ({src} {d['total_delta_pct']:+.2f}% within "
                     f"{d['threshold_pct']:g}%)"))
    return "\n".join(L)


# ---------------------------------------------------------------- CLI
def _load(directory: str) -> List[Dict[str, Any]]:
    from ..observability import tracing
    return tracing.load_trace_dir(directory)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "diff":
        return _main_diff(argv[1:])
    if argv and argv[0] == "summary":
        argv = argv[1:]
    p = argparse.ArgumentParser(
        prog="paddle2_tpu.tools.serve_doctor",
        description="per-request latency decomposition + tail "
                    "attribution from the request-tracing plane "
                    "(see also: the `diff` subcommand)")
    p.add_argument("trace_dir", nargs="?",
                   default=os.environ.get("PADDLE_TRACE_DIR"),
                   help="directory holding trace_rank_N.jsonl "
                        "(default: $PADDLE_TRACE_DIR)")
    p.add_argument("--metrics-dir",
                   default=os.environ.get("PADDLE_METRICS_DIR"),
                   help="metrics dir to join the SLO ledger from "
                        "(default: $PADDLE_METRICS_DIR)")
    p.add_argument("--json", action="store_true",
                   help="emit the structured report as JSON")
    args = p.parse_args(argv)
    if not args.trace_dir:
        p.error("no trace dir: pass one or set PADDLE_TRACE_DIR")
    report = summarize(_load(args.trace_dir),
                       metrics_dir=args.metrics_dir)
    if args.json:
        report = dict(report)
        report.pop("decompositions", None)     # bulky; --json is triage
        print(json.dumps(report, indent=2, default=str))
    else:
        print(format_summary(report, args.trace_dir))
    if report["exactness"]["violations"]:
        return 3
    return 0 if report["finished"] else 2


def _main_diff(argv: List[str]) -> int:
    p = argparse.ArgumentParser(
        prog="paddle2_tpu.tools.serve_doctor diff",
        description="diff two trace streams; exits "
                    f"{REGRESSION_EXIT} on regression (CI gate)")
    p.add_argument("base_dir", help="baseline trace dir (or file)")
    p.add_argument("new_dir", help="candidate trace dir (or file)")
    p.add_argument("--threshold", type=float, default=10.0,
                   help="e2e regression %% (p99 or mean) that fails "
                        "the gate (default 10)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    base = summarize(_load(args.base_dir))
    new = summarize(_load(args.new_dir))
    if not base["finished"] or not new["finished"]:
        print("serve_doctor diff: one side has no finished requests",
              file=sys.stderr)
        return 2
    d = diff(base, new, threshold_pct=args.threshold)
    if args.json:
        print(json.dumps(d, indent=2, default=str))
    else:
        print(format_diff(d))
    return REGRESSION_EXIT if d["regressed"] else 0


if __name__ == "__main__":
    sys.exit(main())
