"""paddle.utils (reference python/paddle/utils/)."""

from . import unique_name  # noqa: F401
from . import cpp_extension  # noqa: F401
from .lazy_import import try_import  # noqa: F401
from .deprecated import deprecated  # noqa: F401

__all__ = ["unique_name", "cpp_extension", "try_import", "deprecated",
           "run_check"]


def run_check():
    """paddle.utils.run_check parity: verifies the accelerator works."""
    import jax
    import jax.numpy as jnp
    devs = jax.devices()
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    assert float(y[0, 0]) == 128.0
    print(f"PaddlePaddle (TPU-native) works on {len(devs)} "
          f"{devs[0].platform} device(s).")
