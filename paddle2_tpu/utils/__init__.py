"""paddle.utils (reference python/paddle/utils/)."""

from . import unique_name  # noqa: F401
from . import cpp_extension  # noqa: F401
from .lazy_import import try_import  # noqa: F401
from .deprecated import deprecated  # noqa: F401

__all__ = ["unique_name", "cpp_extension", "try_import", "deprecated",
           "run_check"]


def run_check():
    """paddle.utils.run_check parity: verifies the accelerator works."""
    import jax
    import jax.numpy as jnp
    devs = jax.devices()
    x = jnp.ones((128, 128))
    y = (x @ x).block_until_ready()
    assert float(y[0, 0]) == 128.0
    print(f"PaddlePaddle (TPU-native) works on {len(devs)} "
          f"{devs[0].platform} device(s).")


def require_version(min_version: str, max_version=None):
    """utils require_version (reference utils/__init__.py): assert the
    installed framework version is inside [min_version, max_version]."""
    import re as _re
    from .. import version as _v

    def parse(s):
        out = []
        for part in str(s).split(".")[:3]:
            m = _re.match(r"\d+", part)
            out.append(int(m.group()) if m else 0)
        return tuple(out)

    cur = parse(_v.full_version)

    if cur < parse(min_version):
        raise Exception(
            f"version {_v.full_version} < required minimum {min_version}")
    if max_version is not None and cur > parse(max_version):
        raise Exception(
            f"version {_v.full_version} > allowed maximum {max_version}")
