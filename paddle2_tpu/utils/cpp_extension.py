"""paddle.utils.cpp_extension (reference utils/cpp_extension/: the custom
C++ operator path).

TPU-native custom-op contract: device compute belongs in JAX/Pallas (see
kernels/), but HOST-side custom ops — tokenizers, samplers, feature
decoders — compile here with g++ into a shared library bound via ctypes
(no pybind11 in this image). ``load()`` builds and returns a
CustomOpLibrary whose ``wrap()`` lifts a C function with the flat ABI

    void op(const float* in, int64_t n, float* out)

into a paddle op: eager calls run directly on numpy buffers; under
jit.to_static the op crosses into the graph as a jax.pure_callback.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Callable, List, Optional, Sequence

import numpy as np

__all__ = ["CppExtension", "CUDAExtension", "load", "load_ffi", "setup",
           "CustomOpLibrary", "FFIOpLibrary", "get_build_directory"]


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_EXTENSION_DIR",
                       os.path.expanduser("~/.cache/paddle2_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


def CppExtension(sources: Sequence[str], **kwargs):
    return {"sources": list(sources), "kind": "cpp", **kwargs}


def CUDAExtension(sources: Sequence[str], **kwargs):
    # no CUDA on TPU hosts: .cu sources are rejected, .cc/.cpp compile
    cpp = [s for s in sources if not s.endswith((".cu", ".cuh"))]
    if len(cpp) != len(sources):
        raise ValueError(
            "CUDAExtension on the TPU build: CUDA sources have no target; "
            "express device compute in JAX/Pallas and keep host code in "
            "C++ (.cc/.cpp)")
    return {"sources": cpp, "kind": "cpp", **kwargs}


def setup(name: str = "", ext_modules=None, **kwargs):
    """setup() parity: builds each extension into the cache dir."""
    exts = ext_modules if isinstance(ext_modules, list) else [ext_modules]
    return [load(name or f"ext{i}", e["sources"])
            for i, e in enumerate(exts) if e]


class CustomOpLibrary:
    def __init__(self, name: str, path: str):
        self.name = name
        self.path = path
        self._lib = ctypes.CDLL(path)

    def raw(self) -> ctypes.CDLL:
        return self._lib

    def wrap(self, fn_name: str, out_shape: Optional[Callable] = None,
             dtype="float32") -> Callable:
        """Lift `void fn(const T* in, int64_t n, T* out)` into a paddle op.

        out_shape(in_shape) -> output shape (default: same shape).
        """
        cfn = getattr(self._lib, fn_name)
        cfn.restype = None
        np_dt = np.dtype(dtype)
        cptr = ctypes.POINTER({
            "float32": ctypes.c_float, "float64": ctypes.c_double,
            "int32": ctypes.c_int32, "int64": ctypes.c_int64,
        }[str(np_dt)])
        cfn.argtypes = [cptr, ctypes.c_int64, cptr]

        def host_call(arr: np.ndarray) -> np.ndarray:
            arr = np.ascontiguousarray(arr, np_dt)
            shape = out_shape(arr.shape) if out_shape else arr.shape
            out = np.empty(shape, np_dt)
            cfn(arr.ctypes.data_as(cptr), arr.size,
                out.ctypes.data_as(cptr))
            return out

        def op(x):
            import jax
            import jax.numpy as jnp
            from paddle2_tpu.ops.dispatch import apply_op, ensure_tensor
            t = ensure_tensor(x)

            def f(a):
                shape = out_shape(a.shape) if out_shape else a.shape
                return jax.pure_callback(
                    host_call, jax.ShapeDtypeStruct(shape, np_dt), a)
            return apply_op(f"custom_{fn_name}", f, (t,), {},
                            differentiable=False)

        op.__name__ = fn_name
        return op


def load(name: str, sources: Sequence[str], extra_cxx_flags=None,
         extra_cuda_cflags=None, extra_ldflags=None,
         extra_include_paths=None, build_directory=None,
         verbose: bool = False, cls=None, **kwargs) -> CustomOpLibrary:
    """utils/cpp_extension/extension_utils.py load() parity: just-in-time
    g++ build, content-hashed cache."""
    build_dir = build_directory or get_build_directory()
    blobs = []
    for s in sources:
        with open(s, "rb") as f:
            blobs.append(f.read())
    # headers in the source dirs + include paths participate in the hash
    # so edits trigger rebuilds
    hdr_dirs = {os.path.dirname(os.path.abspath(s)) for s in sources}
    hdr_dirs.update(os.path.abspath(p) for p in (extra_include_paths or []))
    for d in sorted(hdr_dirs):
        if not os.path.isdir(d):
            continue  # g++ ignores missing -I dirs; so does the hash
        for root, _dirs, files in sorted(os.walk(d)):
            for fname in sorted(files):
                if fname.endswith((".h", ".hpp", ".hh", ".cuh")):
                    with open(os.path.join(root, fname), "rb") as f:
                        blobs.append(f.read())
    key = repr((extra_cxx_flags, extra_ldflags, extra_include_paths))
    tag = hashlib.sha256(b"".join(blobs)
                         + key.encode()).hexdigest()[:16]
    out = os.path.join(build_dir, f"{name}_{tag}.so")
    if not os.path.exists(out):
        cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17"]
               + [f"-I{p}" for p in (extra_include_paths or [])]
               + (extra_cxx_flags or []) + list(sources)
               + ["-o", out + f".{os.getpid()}.tmp"]
               + (extra_ldflags or []))
        subprocess.run(cmd, check=True,
                       capture_output=not verbose)
        os.replace(out + f".{os.getpid()}.tmp", out)
    return (cls or CustomOpLibrary)(name, out)


# ---------------------------------------------------------------- XLA FFI

class FFIOpLibrary(CustomOpLibrary):
    """Custom ops through the XLA FFI (the modern analog of the
    reference's phi/capi custom-KERNEL registration, paddle/phi/capi/):
    the C++ handler compiles against jax.ffi's headers, registers as an
    XLA custom-call target, and executes INSIDE compiled programs on the
    cpu platform with zero Python per call — unlike the ctypes
    pure_callback path, which round-trips the interpreter every
    invocation. Device (TPU) compute still belongs in Pallas; FFI ops
    cover host-side pipelines and CPU-backend deployments."""

    def wrap_ffi(self, symbol: str, target: Optional[str] = None,
                 out_shape: Optional[Callable] = None,
                 dtype="float32", platform: str = "cpu") -> Callable:
        """Register handler `symbol` (declared with
        XLA_FFI_DEFINE_HANDLER_SYMBOL) as custom-call target `target`
        for `platform` and return a paddle op calling it via
        jax.ffi.ffi_call. A CPU-only handler invoked while another
        backend is active raises a clear error up front (a TPU custom
        call would otherwise fail with an opaque 'target not found';
        device compute belongs in Pallas)."""
        import jax

        target = target or f"{self.name}_{symbol}"
        handler = getattr(self._lib, symbol)
        jax.ffi.register_ffi_target(
            target, jax.ffi.pycapsule(handler), platform=platform)
        np_dt = np.dtype(dtype)

        def op(x):
            from paddle2_tpu.ops.dispatch import apply_op, ensure_tensor
            active = jax.devices()[0].platform.lower()
            if active != platform.lower():
                raise RuntimeError(
                    f"FFI op {target!r} is registered for platform "
                    f"{platform!r} but the active backend is {active!r}."
                    " Host-side FFI ops run on the cpu backend; express "
                    "TPU device compute in Pallas (kernels/), or use "
                    "CustomOpLibrary.wrap() for a host callback that "
                    "works from any backend.")
            t = ensure_tensor(x)

            def f(a):
                shape = out_shape(a.shape) if out_shape else a.shape
                return jax.ffi.ffi_call(
                    target, jax.ShapeDtypeStruct(shape, np_dt))(a)
            return apply_op(f"ffi_{target}", f, (t,), {},
                            differentiable=False)

        op.__name__ = symbol
        return op


def load_ffi(name: str, sources: Sequence[str], **kwargs) -> FFIOpLibrary:
    """Build an XLA-FFI custom-op library (adds jax.ffi's include dir to
    the compile; same content-hashed cache as load())."""
    import jax
    inc = list(kwargs.pop("extra_include_paths", []) or [])
    inc.append(jax.ffi.include_dir())
    return load(name, sources, extra_include_paths=inc, cls=FFIOpLibrary,
                **kwargs)
