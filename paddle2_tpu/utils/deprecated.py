"""paddle.utils.deprecated decorator parity."""

import functools
import warnings


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 1):
    def decorator(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            msg = (f"API {func.__name__!r} is deprecated since {since}"
                   + (f", use {update_to!r} instead" if update_to else "")
                   + (f": {reason}" if reason else ""))
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)
        return wrapper
    return decorator
