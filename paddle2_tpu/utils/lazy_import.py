"""paddle.utils.lazy_import parity."""

import importlib


def try_import(module_name: str, err_msg: str = None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or
                          f"required module {module_name!r} is not "
                          "installed (offline build: no pip available)")
