"""paddle.utils.unique_name parity."""

import contextlib
import threading

_tls = threading.local()


def _counters():
    if not hasattr(_tls, "c"):
        _tls.c = {}
    return _tls.c


def generate(key: str) -> str:
    c = _counters()
    c[key] = c.get(key, -1) + 1
    return f"{key}_{c[key]}"


def switch(new_generator=None):
    old = dict(_counters())
    _tls.c = new_generator or {}
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        _tls.c = old
