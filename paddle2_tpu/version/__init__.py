"""paddle.version parity (this framework's own versioning)."""

full_version = "3.0.0-tpu.2"
major = "3"
minor = "0"
patch = "0"
rc = "0"
cuda_version = "False"
cudnn_version = "False"
xpu_version = "False"
istaged = True
commit = "tpu-native"
with_pip_cuda_libraries = "OFF"


def show():
    print(f"paddle2_tpu {full_version} (commit {commit}, TPU/XLA backend)")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
