"""paddle.vision (reference python/paddle/vision/__init__.py)."""

from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from .models import *  # noqa: F401,F403

_image_backend = "pil"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image honoring the backend: 'pil' -> PIL.Image,
    'cv2' -> HWC BGR uint8 ndarray, 'tensor' -> CHW float Tensor."""
    import numpy as np
    from .datasets import default_loader
    b = backend or _image_backend
    if b not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {b!r}")
    img = default_loader(path)
    if b == "pil" or path.endswith(".npy"):
        return img
    arr = np.asarray(img)
    if b == "cv2":
        return arr[:, :, ::-1].copy() if arr.ndim == 3 else arr
    from .transforms import functional as TF
    return TF.to_tensor(arr)
