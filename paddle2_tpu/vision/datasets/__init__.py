"""paddle.vision.datasets (reference python/paddle/vision/datasets/).

Offline build: the reference auto-downloads from bcebos; here every dataset
consumes LOCAL files only and raises a clear error when they're absent.
DatasetFolder/ImageFolder work on any local directory tree.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, List, Optional, Tuple

import numpy as np

from ...io.dataloader import Dataset

__all__ = ["DatasetFolder", "ImageFolder", "MNIST", "FashionMNIST",
           "Cifar10", "Cifar100", "Flowers", "VOC2012"]

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


def _pil_loader(path):
    from PIL import Image
    with open(path, "rb") as f:
        return Image.open(f).convert("RGB")


def default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    return _pil_loader(path)


class DatasetFolder(Dataset):
    """Class-per-subdirectory dataset (datasets/folder.py:37 parity)."""

    def __init__(self, root, loader: Optional[Callable] = None,
                 extensions=IMG_EXTENSIONS, transform=None,
                 is_valid_file: Optional[Callable] = None):
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders found in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        if is_valid_file is None:
            def is_valid_file(p):
                return p.lower().endswith(tuple(extensions))
        samples: List[Tuple[str, int]] = []
        for c in classes:
            d = os.path.join(root, c)
            for base, _, files in sorted(os.walk(d)):
                for fname in sorted(files):
                    p = os.path.join(base, fname)
                    if is_valid_file(p):
                        samples.append((p, self.class_to_idx[c]))
        if not samples:
            raise RuntimeError(f"found 0 files in subfolders of {root}")
        self.samples = samples
        self.targets = [t for _, t in samples]

    def __getitem__(self, index):
        path, target = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat image list, no labels (datasets/folder.py:252 parity)."""

    def __init__(self, root, loader: Optional[Callable] = None,
                 extensions=IMG_EXTENSIONS, transform=None,
                 is_valid_file: Optional[Callable] = None):
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if is_valid_file is None:
            def is_valid_file(p):
                return p.lower().endswith(tuple(extensions))
        samples = []
        for base, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                p = os.path.join(base, fname)
                if is_valid_file(p):
                    samples.append(p)
        if not samples:
            raise RuntimeError(f"found 0 files in {root}")
        self.samples = samples

    def __getitem__(self, index):
        sample = self.loader(self.samples[index])
        if self.transform is not None:
            sample = self.transform(sample)
        return (sample,)

    def __len__(self):
        return len(self.samples)


def _require(path, what):
    if path is None or not os.path.exists(path):
        raise ValueError(
            f"{what}: file not found ({path!r}). This offline build cannot "
            "download datasets; pass the local path explicitly.")
    return path


class MNIST(Dataset):
    """IDX-format MNIST reader (datasets/mnist.py:30 parity, local files
    only: pass image_path/label_path to the raw idx*-ubyte(.gz) files)."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend="cv2"):
        self.mode = mode
        self.transform = transform
        image_path = _require(image_path, f"{self.NAME} images")
        label_path = _require(label_path, f"{self.NAME} labels")
        self.images = self._read_images(image_path)
        self.labels = self._read_labels(label_path)

    @staticmethod
    def _open(path):
        return gzip.open(path, "rb") if path.endswith(".gz") else \
            open(path, "rb")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n).astype("int64")

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """CIFAR python-pickle tarball reader (datasets/cifar.py:30 parity,
    local data_file only)."""

    _train_members = [f"data_batch_{i}" for i in range(1, 6)]
    _test_members = ["test_batch"]
    _label_key = b"labels"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2"):
        self.transform = transform
        data_file = _require(data_file, type(self).__name__)
        members = (self._train_members if mode == "train"
                   else self._test_members)
        xs, ys = [], []
        with tarfile.open(data_file) as tar:
            for m in tar.getmembers():
                base = os.path.basename(m.name)
                if base in members:
                    d = pickle.load(tar.extractfile(m), encoding="bytes")
                    xs.append(np.asarray(d[b"data"], np.uint8))
                    ys.extend(d[self._label_key])
        if not xs:
            raise ValueError(f"no {mode} batches found in {data_file}")
        self.data = np.concatenate(xs).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(ys, "int64")

    def __getitem__(self, idx):
        img = self.data[idx].transpose(1, 2, 0)  # HWC for transforms
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    _train_members = ["train"]
    _test_members = ["test"]
    _label_key = b"fine_labels"


class Flowers(Dataset):
    """Oxford 102 Flowers (datasets/flowers.py parity, local files only:
    data_file = extracted jpg directory, label_file = imagelabels .mat
    or a plain text file of one label per line)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=False,
                 backend="cv2"):
        import numpy as np
        self.transform = transform
        root = _require(data_file, "Flowers")
        files = sorted(f for f in os.listdir(root)
                       if f.lower().endswith((".jpg", ".png")))
        self.files = [os.path.join(root, f) for f in files]
        if label_file and os.path.exists(label_file):
            if label_file.endswith(".mat"):
                raise ValueError(
                    "scipy .mat labels are not parseable offline; convert "
                    "imagelabels.mat to a text file of one label per line")
            with open(label_file) as f:
                self.labels = [int(x) for x in f.read().split()]
            if len(self.labels) != len(self.files):
                raise ValueError(
                    f"Flowers: {len(self.labels)} labels for "
                    f"{len(self.files)} images — the label file must "
                    "have one entry per jpg")
        else:
            self.labels = [0] * len(self.files)

    def __getitem__(self, idx):
        img = default_loader(self.files[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.files)


class VOC2012(Dataset):
    """Pascal VOC 2012 segmentation pairs (datasets/voc2012.py parity,
    local extraction only: data_file = VOCdevkit/VOC2012 root)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend="cv2"):
        self.transform = transform
        root = _require(data_file, "VOC2012")
        split = {"train": "train", "valid": "val", "test": "val"}.get(
            mode, "train")
        listing = os.path.join(root, "ImageSets", "Segmentation",
                               split + ".txt")
        _require(listing, "VOC2012 split list")
        with open(listing) as f:
            names = [line.strip() for line in f if line.strip()]
        self.images = [os.path.join(root, "JPEGImages", n + ".jpg")
                       for n in names]
        self.masks = [os.path.join(root, "SegmentationClass", n + ".png")
                      for n in names]

    def __getitem__(self, idx):
        img = default_loader(self.images[idx])
        mask = default_loader(self.masks[idx])
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self.images)
