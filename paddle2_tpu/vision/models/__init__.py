"""paddle.vision.models (reference python/paddle/vision/models/__init__.py)."""

from .resnet import (ResNet, BasicBlock, BottleneckBlock, resnet18,
                     resnet34, resnet50, resnet101, resnet152,
                     resnext50_32x4d, resnext50_64x4d, resnext101_32x4d,
                     resnext101_64x4d, resnext152_32x4d, resnext152_64x4d,
                     wide_resnet50_2, wide_resnet101_2)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .small import (LeNet, AlexNet, SqueezeNet, alexnet, squeezenet1_0,
                    squeezenet1_1)
from .mobilenet import (MobileNetV1, MobileNetV2, MobileNetV3Small,
                        MobileNetV3Large, mobilenet_v1, mobilenet_v2,
                        mobilenet_v3_small, mobilenet_v3_large)

__all__ = [
    "ResNet", "BasicBlock", "BottleneckBlock", "resnet18", "resnet34",
    "resnet50", "resnet101", "resnet152", "resnext50_32x4d",
    "resnext50_64x4d", "resnext101_32x4d", "resnext101_64x4d",
    "resnext152_32x4d", "resnext152_64x4d", "wide_resnet50_2",
    "wide_resnet101_2", "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "LeNet", "AlexNet", "SqueezeNet", "alexnet", "squeezenet1_0",
    "squeezenet1_1", "MobileNetV1", "MobileNetV2", "MobileNetV3Small",
    "MobileNetV3Large", "mobilenet_v1", "mobilenet_v2",
    "mobilenet_v3_small", "mobilenet_v3_large",
]
