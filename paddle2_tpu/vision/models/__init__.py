"""paddle.vision.models (reference python/paddle/vision/models/__init__.py)."""

from .resnet import (ResNet, BasicBlock, BottleneckBlock, resnet18,
                     resnet34, resnet50, resnet101, resnet152,
                     resnext50_32x4d, resnext50_64x4d, resnext101_32x4d,
                     resnext101_64x4d, resnext152_32x4d, resnext152_64x4d,
                     wide_resnet50_2, wide_resnet101_2)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19
from .small import (LeNet, AlexNet, SqueezeNet, alexnet, squeezenet1_0,
                    squeezenet1_1)
from .mobilenet import (MobileNetV1, MobileNetV2, MobileNetV3Small,
                        MobileNetV3Large, mobilenet_v1, mobilenet_v2,
                        mobilenet_v3_small, mobilenet_v3_large)
from .densenet import (DenseNet, densenet121, densenet161, densenet169,
                       densenet201, densenet264)
from .inception_shuffle import (GoogLeNet, googlenet, InceptionV3,
                                inception_v3, ShuffleNetV2,
                                shufflenet_v2_x0_25, shufflenet_v2_x0_33,
                                shufflenet_v2_x0_5,
                                shufflenet_v2_x1_0, shufflenet_v2_x1_5,
                                shufflenet_v2_x2_0, shufflenet_v2_swish)

__all__ = [
    "ResNet", "BasicBlock", "BottleneckBlock", "resnet18", "resnet34",
    "resnet50", "resnet101", "resnet152", "resnext50_32x4d",
    "resnext50_64x4d", "resnext101_32x4d", "resnext101_64x4d",
    "resnext152_32x4d", "resnext152_64x4d", "wide_resnet50_2",
    "wide_resnet101_2", "VGG", "vgg11", "vgg13", "vgg16", "vgg19",
    "LeNet", "AlexNet", "SqueezeNet", "alexnet", "squeezenet1_0",
    "squeezenet1_1", "MobileNetV1", "MobileNetV2", "MobileNetV3Small",
    "MobileNetV3Large", "mobilenet_v1", "mobilenet_v2",
    "mobilenet_v3_small", "mobilenet_v3_large",
    "DenseNet", "densenet121", "densenet161", "densenet169",
    "densenet201", "densenet264", "GoogLeNet", "googlenet",
    "InceptionV3", "inception_v3", "ShuffleNetV2",
    "shufflenet_v2_x0_25", "shufflenet_v2_x0_33", "shufflenet_v2_x0_5",
    "shufflenet_v2_x1_0",
    "shufflenet_v2_x1_5", "shufflenet_v2_x2_0", "shufflenet_v2_swish",
]
