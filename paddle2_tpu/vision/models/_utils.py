"""Shared helpers for the vision model zoo."""


def no_pretrained(pretrained):
    """This offline build cannot download weights (the reference pulls from
    bcebos); load them explicitly via paddle.load + set_state_dict."""
    if pretrained:
        raise ValueError(
            "pretrained=True is unavailable offline; use paddle.load + "
            "set_state_dict with a local weights file")
