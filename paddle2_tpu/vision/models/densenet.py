"""DenseNet family (reference python/paddle/vision/models/densenet.py:255;
independent reimplementation)."""

from __future__ import annotations

from ... import nn
from ...ops.manipulation import concat
from ._utils import no_pretrained

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFGS = {121: (64, 32, [6, 12, 24, 16]),
         161: (96, 48, [6, 12, 36, 24]),
         169: (64, 32, [6, 12, 32, 32]),
         201: (64, 32, [6, 12, 48, 32]),
         264: (64, 32, [6, 12, 64, 48])}


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return concat([x, out], axis=1)


class _DenseBlock(nn.Layer):
    def __init__(self, n_layers, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.layers = nn.LayerList([
            _DenseLayer(in_c + i * growth_rate, growth_rate, bn_size,
                        dropout) for i in range(n_layers)])

    def forward(self, x):
        for l in self.layers:
            x = l(x)
        return x


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.relu = nn.ReLU()
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, 2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    """densenet.py:255 parity (layers in {121,161,169,201,264})."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        init_c, growth, cfg = _CFGS[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(init_c), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1))
        blocks = []
        c = init_c
        for i, n in enumerate(cfg):
            blocks.append(_DenseBlock(n, c, growth, bn_size, dropout))
            c += n * growth
            if i != len(cfg) - 1:
                blocks.append(_Transition(c, c // 2))
                c //= 2
        self.features = nn.Sequential(*blocks)
        self.bn_last = nn.BatchNorm2D(c)
        self.relu = nn.ReLU()
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.relu(self.bn_last(self.features(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _make(layers, pretrained, **kwargs):
    no_pretrained(pretrained)
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _make(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _make(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _make(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _make(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _make(264, pretrained, **kwargs)
