"""GoogLeNet, InceptionV3, ShuffleNetV2 (reference
python/paddle/vision/models/{googlenet.py:118, inceptionv3.py:478,
shufflenetv2.py:204}; independent reimplementations)."""

from __future__ import annotations

import jax.numpy as jnp

from ... import nn
from ...framework.tensor import Tensor
from ...ops.dispatch import apply_op
from ...ops.manipulation import concat
from ._utils import no_pretrained

__all__ = ["GoogLeNet", "googlenet", "InceptionV3", "inception_v3",
           "ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5",
           "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]


class _BNConv(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=padding, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = {"relu": nn.ReLU, "swish": nn.Silu,
                    None: None}[act]
        self.act = self.act() if self.act else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act else x


# ------------------------------------------------------------- GoogLeNet

class _Inception(nn.Layer):
    def __init__(self, in_c, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _BNConv(in_c, c1, 1)
        self.b2 = nn.Sequential(_BNConv(in_c, c3r, 1),
                                _BNConv(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_BNConv(in_c, c5r, 1),
                                _BNConv(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, padding=1),
                                _BNConv(in_c, proj, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(nn.Layer):
    """googlenet.py:118 capability (main classifier only — the reference's
    two auxiliary heads are a train-time regularizer that batch-norm
    largely obsoletes; forward returns ONE tensor)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BNConv(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, 2, padding=1),
            _BNConv(64, 64, 1), _BNConv(64, 192, 3, padding=1),
            nn.MaxPool2D(3, 2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.pool5 = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x)))))
        x = self.pool4(x)
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool5(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.flatten(1)))
        return x


def googlenet(pretrained=False, **kwargs):
    no_pretrained(pretrained)
    return GoogLeNet(**kwargs)


# ------------------------------------------------------------ InceptionV3

class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_c):
        super().__init__()
        self.b1 = _BNConv(in_c, 64, 1)
        self.b5 = nn.Sequential(_BNConv(in_c, 48, 1),
                                _BNConv(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_BNConv(in_c, 64, 1),
                                _BNConv(64, 96, 3, padding=1),
                                _BNConv(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _BNConv(in_c, pool_c, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], 1)


class _InceptionB(nn.Layer):  # grid reduction
    def __init__(self, in_c):
        super().__init__()
        self.b3 = _BNConv(in_c, 384, 3, stride=2)
        self.b33 = nn.Sequential(_BNConv(in_c, 64, 1),
                                 _BNConv(64, 96, 3, padding=1),
                                 _BNConv(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return concat([self.b3(x), self.b33(x), self.pool(x)], 1)


class _InceptionC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _BNConv(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _BNConv(in_c, c7, 1),
            _BNConv(c7, c7, (1, 7), padding=(0, 3)),
            _BNConv(c7, 192, (7, 1), padding=(3, 0)))
        self.b77 = nn.Sequential(
            _BNConv(in_c, c7, 1),
            _BNConv(c7, c7, (7, 1), padding=(3, 0)),
            _BNConv(c7, c7, (1, 7), padding=(0, 3)),
            _BNConv(c7, c7, (7, 1), padding=(3, 0)),
            _BNConv(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _BNConv(in_c, 192, 1))

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b77(x), self.bp(x)], 1)


class _InceptionD(nn.Layer):  # grid reduction
    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_BNConv(in_c, 192, 1),
                                _BNConv(192, 320, 3, stride=2))
        self.b7 = nn.Sequential(
            _BNConv(in_c, 192, 1),
            _BNConv(192, 192, (1, 7), padding=(0, 3)),
            _BNConv(192, 192, (7, 1), padding=(3, 0)),
            _BNConv(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, 2)

    def forward(self, x):
        return concat([self.b3(x), self.b7(x), self.pool(x)], 1)


class _InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _BNConv(in_c, 320, 1)
        self.b3_stem = _BNConv(in_c, 384, 1)
        self.b3_a = _BNConv(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _BNConv(384, 384, (3, 1), padding=(1, 0))
        self.b33_stem = nn.Sequential(_BNConv(in_c, 448, 1),
                                      _BNConv(448, 384, 3, padding=1))
        self.b33_a = _BNConv(384, 384, (1, 3), padding=(0, 1))
        self.b33_b = _BNConv(384, 384, (3, 1), padding=(1, 0))
        self.bp = nn.Sequential(nn.AvgPool2D(3, 1, padding=1),
                                _BNConv(in_c, 192, 1))

    def forward(self, x):
        s3 = self.b3_stem(x)
        s33 = self.b33_stem(x)
        return concat([self.b1(x),
                       concat([self.b3_a(s3), self.b3_b(s3)], 1),
                       concat([self.b33_a(s33), self.b33_b(s33)], 1),
                       self.bp(x)], 1)


class InceptionV3(nn.Layer):
    """inceptionv3.py:478 parity (299x299 inputs)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BNConv(3, 32, 3, stride=2), _BNConv(32, 32, 3),
            _BNConv(32, 64, 3, padding=1), nn.MaxPool2D(3, 2),
            _BNConv(64, 80, 1), _BNConv(80, 192, 3), nn.MaxPool2D(3, 2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64),
            _InceptionA(288, 64), _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768), _InceptionE(1280), _InceptionE(2048))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.5)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.flatten(1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    no_pretrained(pretrained)
    return InceptionV3(**kwargs)


# ----------------------------------------------------------- ShuffleNetV2

def _channel_shuffle(x: Tensor, groups: int) -> Tensor:
    def f(a):
        b, c, h, w = a.shape
        return (a.reshape(b, groups, c // groups, h, w)
                .swapaxes(1, 2).reshape(b, c, h, w))
    return apply_op("channel_shuffle", f, (x,), {})


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_c, out_c, stride, act):
        super().__init__()
        self.stride = stride
        mid = out_c // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _BNConv(in_c // 2, mid, 1, act=act),
                _BNConv(mid, mid, 3, stride=1, padding=1, groups=mid,
                        act=None),
                _BNConv(mid, mid, 1, act=act))
            self.branch1 = None
        else:
            self.branch1 = nn.Sequential(
                _BNConv(in_c, in_c, 3, stride=stride, padding=1,
                        groups=in_c, act=None),
                _BNConv(in_c, mid, 1, act=act))
            self.branch2 = nn.Sequential(
                _BNConv(in_c, mid, 1, act=act),
                _BNConv(mid, mid, 3, stride=stride, padding=1, groups=mid,
                        act=None),
                _BNConv(mid, mid, 1, act=act))

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = concat([x1, self.branch2(x2)], 1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], 1)
        return _channel_shuffle(out, 2)


_SHUFFLE_CFG = {0.25: [24, 24, 48, 96, 512],
                0.33: [24, 32, 64, 128, 512],
                0.5: [24, 48, 96, 192, 1024],
                1.0: [24, 116, 232, 464, 1024],
                1.5: [24, 176, 352, 704, 1024],
                2.0: [24, 244, 488, 976, 2048]}


class ShuffleNetV2(nn.Layer):
    """shufflenetv2.py:204 parity."""

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        cfg = _SHUFFLE_CFG[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _BNConv(3, cfg[0], 3, stride=2, padding=1, act=act),
            nn.MaxPool2D(3, 2, padding=1))
        stages = []
        in_c = cfg[0]
        for i, reps in enumerate([4, 8, 4]):
            out_c = cfg[i + 1]
            units = [_ShuffleUnit(in_c, out_c, 2, act)]
            units += [_ShuffleUnit(out_c, out_c, 1, act)
                      for _ in range(reps - 1)]
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.last = _BNConv(in_c, cfg[4], 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(cfg[4], num_classes)

    def forward(self, x):
        x = self.last(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _shuffle(scale, pretrained, act="relu", **kwargs):
    no_pretrained(pretrained)
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shuffle(0.25, pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shuffle(0.33, pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shuffle(0.5, pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shuffle(1.0, pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shuffle(1.5, pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shuffle(2.0, pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shuffle(1.0, pretrained, act="swish", **kwargs)
