"""MobileNet V1/V2/V3 (reference models mobilenetv1.py:80,
mobilenetv2.py:94, mobilenetv3.py:147; independent reimplementations)."""

from __future__ import annotations

from ... import nn
from ._utils import no_pretrained

__all__ = ["MobileNetV1", "MobileNetV2", "MobileNetV3Small",
           "MobileNetV3Large", "mobilenet_v1", "mobilenet_v2",
           "mobilenet_v3_small", "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _ConvBNRelu(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, groups=1, act=nn.ReLU):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.act = act() if act is not None else None

    def forward(self, x):
        x = self.bn(self.conv(x))
        return self.act(x) if self.act is not None else x


class MobileNetV1(nn.Layer):
    """Depthwise-separable stack (mobilenetv1.py:80)."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: int(c * scale)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_ConvBNRelu(3, s(32), 3, stride=2)]
        for in_c, out_c, stride in cfg:
            layers.append(_ConvBNRelu(s(in_c), s(in_c), 3, stride=stride,
                                      groups=s(in_c)))   # depthwise
            layers.append(_ConvBNRelu(s(in_c), s(out_c), 1))  # pointwise
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


class _InvertedResidual(nn.Layer):
    """mobilenetv2.py:39 parity."""

    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers.append(_ConvBNRelu(in_c, hidden, 1, act=nn.ReLU6))
        layers += [
            _ConvBNRelu(hidden, hidden, 3, stride=stride, groups=hidden,
                        act=nn.ReLU6),
            _ConvBNRelu(hidden, out_c, 1, act=None),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    """mobilenetv2.py:94 parity."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        layers = [_ConvBNRelu(3, in_c, 3, stride=2, act=nn.ReLU6)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                layers.append(_InvertedResidual(in_c, out_c,
                                                s if i == 0 else 1, t))
                in_c = out_c
        layers.append(_ConvBNRelu(in_c, last_c, 1, act=nn.ReLU6))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class _SqueezeExcite(nn.Layer):
    def __init__(self, c, r=4):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(c, _make_divisible(c // r), 1)
        self.fc2 = nn.Conv2D(_make_divisible(c // r), c, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.relu(self.fc1(self.pool(x)))
        return x * self.hsig(self.fc2(s))


class _V3Block(nn.Layer):
    def __init__(self, in_c, exp, out_c, k, stride, se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp != in_c:
            layers.append(_ConvBNRelu(in_c, exp, 1, act=act))
        layers.append(_ConvBNRelu(exp, exp, k, stride=stride, groups=exp,
                                  act=act))
        if se:
            layers.append(_SqueezeExcite(exp))
        layers.append(_ConvBNRelu(exp, out_c, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_SMALL = [  # k, exp, out, se, act, stride  (mobilenetv3.py small cfg)
    (3, 16, 16, True, nn.ReLU, 2), (3, 72, 24, False, nn.ReLU, 2),
    (3, 88, 24, False, nn.ReLU, 1), (5, 96, 40, True, nn.Hardswish, 2),
    (5, 240, 40, True, nn.Hardswish, 1), (5, 240, 40, True, nn.Hardswish, 1),
    (5, 120, 48, True, nn.Hardswish, 1), (5, 144, 48, True, nn.Hardswish, 1),
    (5, 288, 96, True, nn.Hardswish, 2), (5, 576, 96, True, nn.Hardswish, 1),
    (5, 576, 96, True, nn.Hardswish, 1)]

_V3_LARGE = [
    (3, 16, 16, False, nn.ReLU, 1), (3, 64, 24, False, nn.ReLU, 2),
    (3, 72, 24, False, nn.ReLU, 1), (5, 72, 40, True, nn.ReLU, 2),
    (5, 120, 40, True, nn.ReLU, 1), (5, 120, 40, True, nn.ReLU, 1),
    (3, 240, 80, False, nn.Hardswish, 2), (3, 200, 80, False, nn.Hardswish, 1),
    (3, 184, 80, False, nn.Hardswish, 1), (3, 184, 80, False, nn.Hardswish, 1),
    (3, 480, 112, True, nn.Hardswish, 1), (3, 672, 112, True, nn.Hardswish, 1),
    (5, 672, 160, True, nn.Hardswish, 2), (5, 960, 160, True, nn.Hardswish, 1),
    (5, 960, 160, True, nn.Hardswish, 1)]


class _MobileNetV3(nn.Layer):
    """mobilenetv3.py:147 parity."""

    def __init__(self, cfg, last_exp, last_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        s = lambda c: _make_divisible(c * scale)
        in_c = s(16)
        layers = [_ConvBNRelu(3, in_c, 3, stride=2, act=nn.Hardswish)]
        for k, exp, out_c, se, act, stride in cfg:
            layers.append(_V3Block(in_c, s(exp), s(out_c), k, stride, se,
                                   act))
            in_c = s(out_c)
        layers.append(_ConvBNRelu(in_c, s(last_exp), 1, act=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(s(last_exp), last_c), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, 1024, scale, num_classes, with_pool)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, 1280, scale, num_classes, with_pool)




def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    no_pretrained(pretrained)
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    no_pretrained(pretrained)
    return MobileNetV2(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    no_pretrained(pretrained)
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    no_pretrained(pretrained)
    return MobileNetV3Large(scale=scale, **kwargs)
