"""paddle.vision.ops (reference python/paddle/vision/ops.py; the detection
primitives re-expressed in jnp — nms runs as an XLA while-loop-free
mask-matrix algorithm instead of the reference's CUDA kernel)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import ensure_tensor

__all__ = ["nms", "box_area", "box_iou", "roi_align", "RoIAlign",
           "deform_conv2d", "DeformConv2D", "psroi_pool", "PSRoIPool",
           "box_coder", "distribute_fpn_proposals", "generate_proposals",
           "read_file", "decode_jpeg", "roi_pool", "RoIPool", "prior_box",
           "yolo_box", "yolo_loss", "matrix_nms"]


def box_area(boxes):
    b = ensure_tensor(boxes)._data
    return Tensor((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def _iou_matrix(a, b):
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-9)


def box_iou(boxes1, boxes2):
    a = ensure_tensor(boxes1)._data
    b = ensure_tensor(boxes2)._data
    return Tensor(_iou_matrix(a, b))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """ops.py nms parity. Returns kept indices sorted by descending score.

    Greedy NMS as a numpy loop on host (data-dependent output size cannot
    trace; the reference's GPU kernel is also a sequential bitmask scan).
    """
    import numpy as np
    b = np.asarray(ensure_tensor(boxes)._data)
    n = b.shape[0]
    s = (np.asarray(ensure_tensor(scores)._data) if scores is not None
         else np.arange(n, 0, -1, dtype="float32"))
    cats = (np.asarray(ensure_tensor(category_idxs)._data)
            if category_idxs is not None else np.zeros(n, "int64"))
    iou = np.asarray(_iou_matrix(jnp.asarray(b), jnp.asarray(b)))
    order = np.argsort(-s)
    keep, suppressed = [], np.zeros(n, bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        overlap = (iou[i] > iou_threshold) & (cats == cats[i])
        suppressed |= overlap
        suppressed[i] = True
    keep = np.asarray(keep, "int64")
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """ops.py roi_align parity (average-pool variant via bilinear grid
    sampling with jnp gathers)."""
    import numpy as np
    xd = ensure_tensor(x)._data
    bx = ensure_tensor(boxes)._data
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    n_num = [int(v) for v in ensure_tensor(boxes_num).numpy()]
    batch_idx = np.repeat(np.arange(len(n_num)), n_num)

    offset = 0.5 if aligned else 0.0
    C = xd.shape[1]
    H, W = xd.shape[2], xd.shape[3]
    outs = []
    ratio = sampling_ratio if sampling_ratio > 0 else 2
    for r in range(bx.shape[0]):
        b = batch_idx[r]
        x1, y1, x2, y2 = [bx[r, i] * spatial_scale - offset for i in range(4)]
        rh = jnp.maximum(y2 - y1, 1e-3) / ph
        rw = jnp.maximum(x2 - x1, 1e-3) / pw
        iy = (jnp.arange(ph)[:, None] + (jnp.arange(ratio) + 0.5)[None, :]
              / ratio).reshape(-1)
        ix = (jnp.arange(pw)[:, None] + (jnp.arange(ratio) + 0.5)[None, :]
              / ratio).reshape(-1)
        ys = y1 + iy * rh                      # (ph*ratio,)
        xs = x1 + ix * rw                      # (pw*ratio,)
        y0 = jnp.clip(jnp.floor(ys), 0, H - 1).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(xs), 0, W - 1).astype(jnp.int32)
        y1i = jnp.clip(y0 + 1, 0, H - 1)
        x1i = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(ys, 0, H - 1) - y0
        wx = jnp.clip(xs, 0, W - 1) - x0
        img = xd[b]                            # (C, H, W)
        v00 = img[:, y0][:, :, x0]
        v01 = img[:, y0][:, :, x1i]
        v10 = img[:, y1i][:, :, x0]
        v11 = img[:, y1i][:, :, x1i]
        val = (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
               + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
               + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
               + v11 * wy[None, :, None] * wx[None, None, :])
        val = val.reshape(C, ph, ratio, pw, ratio).mean(axis=(2, 4))
        outs.append(val)
    return Tensor(jnp.stack(outs)) if outs else Tensor(
        jnp.zeros((0, C, ph, pw), xd.dtype))


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _bilinear_sample(img, ys, xs):
    """Zero-padded bilinear sampling. img [C, H, W]; ys/xs any shape S.
    Returns [C, *S]. Out-of-bounds corners contribute zero (the
    deformable-conv border convention, deformable_conv_kernel.cu)."""
    H, W = img.shape[-2:]
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    wy = ys - y0
    wx = xs - x0

    def corner(yc, xc, w):
        valid = (yc >= 0) & (yc < H) & (xc >= 0) & (xc < W)
        v = img[:, jnp.clip(yc, 0, H - 1), jnp.clip(xc, 0, W - 1)]
        return v * (w * valid)[None]

    return (corner(y0, x0, (1 - wy) * (1 - wx))
            + corner(y0, x0 + 1, (1 - wy) * wx)
            + corner(y0 + 1, x0, wy * (1 - wx))
            + corner(y0 + 1, x0 + 1, wy * wx))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference ops.py:766; CUDA kernel
    deformable_conv_kernel). Each kernel tap samples the input at its
    grid position plus a learned offset (bilinear), optionally scaled by
    a modulation mask (v2), then contracts with the weights — expressed
    here as gather-based sampling + one einsum so XLA fuses it and the
    tape differentiates it."""
    from ..ops.dispatch import apply_op

    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)
    tensors = [ensure_tensor(x), ensure_tensor(offset),
               ensure_tensor(weight)]
    has_mask = mask is not None
    has_bias = bias is not None
    if has_mask:
        tensors.append(ensure_tensor(mask))
    if has_bias:
        tensors.append(ensure_tensor(bias))

    def fn(xd, od, wd, *rest):
        md = rest[0] if has_mask else None
        bd = rest[-1] if has_bias else None
        N, Cin, H, W = xd.shape
        Cout, Cin_g, kh, kw = wd.shape
        K = kh * kw
        dg = deformable_groups
        Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        # base sampling grid per tap
        ys0 = (jnp.arange(Ho) * sh - ph)[None, :, None] \
            + (jnp.arange(kh) * dh).repeat(kw)[:, None, None]
        xs0 = (jnp.arange(Wo) * sw - pw)[None, None, :] \
            + jnp.tile(jnp.arange(kw) * dw, kh)[:, None, None]
        off = od.reshape(N, dg, K, 2, Ho, Wo)
        ys = ys0[None, None] + off[:, :, :, 0]        # [N, dg, K, Ho, Wo]
        xs = xs0[None, None] + off[:, :, :, 1]
        xg = xd.reshape(N, dg, Cin // dg, H, W)

        samp = jax.vmap(jax.vmap(_bilinear_sample))(xg, ys, xs)
        # [N, dg, C/dg, K, Ho, Wo]
        if md is not None:
            samp = samp * md.reshape(N, dg, 1, K, Ho, Wo)
        samp = samp.reshape(N, groups, Cin // groups, K, Ho, Wo)
        wg = wd.reshape(groups, Cout // groups, Cin_g, K)
        out = jnp.einsum("gock,ngckij->ngoij", wg, samp,
                         preferred_element_type=jnp.float32)
        out = out.reshape(N, Cout, Ho, Wo).astype(xd.dtype)
        if bd is not None:
            out = out + bd[None, :, None, None]
        return out

    return apply_op("deform_conv2d", fn, tuple(tensors), {})


def _layer_base():
    from ..nn import Layer
    return Layer


class DeformConv2D(_layer_base()):
    """Layer form of deform_conv2d (reference ops.py DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1,
                 deformable_groups=1, groups=1, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        kh, kw = _pair(kernel_size)
        self._attrs = (stride, padding, dilation,
                       deformable_groups, groups)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr)
        self.bias = None if bias_attr is False else \
            self.create_parameter([out_channels], attr=bias_attr,
                                  is_bias=True)

    def forward(self, x, offset, mask=None):
        s, p, d, dg, g = self._attrs
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=s, padding=p, dilation=d,
                             deformable_groups=dg, groups=g, mask=mask)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive ROI pooling (reference ops.py:1441,
    psroi_pool_kernel): input channels C = out_c * ph * pw; output bin
    (i, j) of channel c average-pools its DEDICATED input channel
    c*ph*pw + i*pw + j over the bin's region."""
    import numpy as np
    xd = ensure_tensor(x)._data
    bx = ensure_tensor(boxes)._data
    ph, pw = _pair(output_size)
    C = xd.shape[1]
    if C % (ph * pw) != 0:
        raise ValueError(
            f"psroi_pool input channels {C} must be divisible by "
            f"output_size {ph}x{pw}")
    out_c = C // (ph * pw)
    H, W = xd.shape[2], xd.shape[3]
    n_num = [int(v) for v in ensure_tensor(boxes_num).numpy()]
    batch_idx = np.repeat(np.arange(len(n_num)), n_num)
    outs = []
    for r in range(bx.shape[0]):
        img = xd[int(batch_idx[r])]  # [C, H, W]
        x1, y1, x2, y2 = [bx[r, i] * spatial_scale for i in range(4)]
        bin_h = (y2 - y1) / ph
        bin_w = (x2 - x1) / pw
        chans = jnp.arange(out_c * ph * pw).reshape(out_c, ph, pw)
        rows = []
        for i in range(ph):
            cols = []
            for j in range(pw):
                hs = jnp.clip(jnp.floor(y1 + i * bin_h), 0, H).astype(int)
                he = jnp.clip(jnp.ceil(y1 + (i + 1) * bin_h), 0, H).astype(int)
                ws = jnp.clip(jnp.floor(x1 + j * bin_w), 0, W).astype(int)
                we = jnp.clip(jnp.ceil(x1 + (j + 1) * bin_w), 0, W).astype(int)
                # dynamic extents: mask-average instead of slicing
                ii = jnp.arange(H)[:, None]
                jj = jnp.arange(W)[None, :]
                m = ((ii >= hs) & (ii < he) & (jj >= ws) & (jj < we))
                area = jnp.maximum(m.sum(), 1)
                vals = (img[chans[:, i, j]] * m[None]).sum((-2, -1)) / area
                empty = (he <= hs) | (we <= ws)
                cols.append(jnp.where(empty, 0.0, vals))
            rows.append(jnp.stack(cols, -1))
        outs.append(jnp.stack(rows, -2))  # [out_c, ph, pw]
    return Tensor(jnp.stack(outs)) if outs else Tensor(
        jnp.zeros((0, out_c, ph, pw), xd.dtype))


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode target boxes against prior (anchor) boxes
    (reference ops.py:584, phi box_coder kernel)."""
    pb = ensure_tensor(prior_box)._data.astype(jnp.float32)
    tb = ensure_tensor(target_box)._data.astype(jnp.float32)
    if isinstance(prior_box_var, (list, tuple)):
        pbv = jnp.asarray(prior_box_var, jnp.float32)
    elif prior_box_var is None:
        pbv = jnp.ones((4,), jnp.float32)
    else:
        pbv = ensure_tensor(prior_box_var)._data.astype(jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    px = pb[:, 0] + pw * 0.5
    py = pb[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        # tb [N, 4] targets vs priors [M, 4] -> [N, M, 4] (the kernel's
        # row = target, col = prior orientation, box_coder kernel)
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tx = tb[:, 0] + tw * 0.5
        ty = tb[:, 1] + th * 0.5
        ox = (tx[:, None] - px[None, :]) / pw[None, :]
        oy = (ty[:, None] - py[None, :]) / ph[None, :]
        ow = jnp.log(tw[:, None] / pw[None, :])
        oh = jnp.log(th[:, None] / ph[None, :])
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        out = out / (pbv.reshape(-1, 4)[None, :] if pbv.ndim == 2
                     else pbv[None, None])
        return Tensor(out)
    if code_type != "decode_center_size":
        raise ValueError(f"unknown code_type {code_type!r}")
    # decode: tb [N, M, 4] deltas; priors broadcast ALONG `axis` (axis=0:
    # PriorBox [M, 4] tiles over dim 0, i.e. priors vary on dim 1)
    if tb.ndim == 2:
        tb = tb[:, None]
    if axis == 0:
        px_, py_, pw_, ph_ = (px[None, :], py[None, :],
                              pw[None, :], ph[None, :])
        var = pbv.reshape(-1, 4)[None, :] if pbv.ndim == 2 \
            else pbv[None, None]
    else:
        px_, py_, pw_, ph_ = (px[:, None], py[:, None],
                              pw[:, None], ph[:, None])
        var = pbv.reshape(-1, 4)[:, None] if pbv.ndim == 2 \
            else pbv[None, None]
    d = tb * var
    ox = d[..., 0] * pw_ + px_
    oy = d[..., 1] * ph_ + py_
    ow = jnp.exp(d[..., 2]) * pw_
    oh = jnp.exp(d[..., 3]) * ph_
    out = jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                     ox + ow * 0.5 - norm, oy + oh * 0.5 - norm],
                    axis=-1)
    return Tensor(out)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Route each ROI to its FPN level by scale (reference ops.py:1200):
    level = floor(refer_level + log2(sqrt(area) / refer_scale)), clipped
    to [min_level, max_level]. Output sizes are data-dependent, so this
    runs eagerly on host values (the reference's is a CPU/GPU kernel with
    dynamic outputs for the same reason)."""
    import numpy as np
    rois = np.asarray(ensure_tensor(fpn_rois).numpy(), np.float32)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8) + refer_level)
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    num_levels = max_level - min_level + 1
    multi_rois, restore_parts, rois_num_per_level = [], [], []
    for i in range(num_levels):
        idx = np.nonzero(lvl == min_level + i)[0]
        multi_rois.append(Tensor(jnp.asarray(rois[idx])))
        restore_parts.append(idx)
        rois_num_per_level.append(Tensor(jnp.asarray([len(idx)],
                                                     jnp.int32)))
    order = np.concatenate(restore_parts) if restore_parts else \
        np.zeros((0,), np.int64)
    restore_ind = np.empty_like(order)
    restore_ind[order] = np.arange(len(order))
    restore = Tensor(jnp.asarray(restore_ind.reshape(-1, 1), jnp.int32))
    if rois_num is not None:
        return multi_rois, restore, rois_num_per_level
    return multi_rois, restore, None


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference ops.py:2159, phi
    generate_proposals kernel): per image, top-k anchors by score ->
    decode deltas -> clip to image -> drop tiny boxes -> NMS -> top-k.
    Output counts are data-dependent: host-eager like the reference's
    kernel launch + dynamic output."""
    import numpy as np
    sc = np.asarray(ensure_tensor(scores).numpy(), np.float32)
    bd = np.asarray(ensure_tensor(bbox_deltas).numpy(), np.float32)
    ims = np.asarray(ensure_tensor(img_size).numpy(), np.float32)
    an = np.asarray(ensure_tensor(anchors).numpy(),
                    np.float32).reshape(-1, 4)
    va = np.asarray(ensure_tensor(variances).numpy(),
                    np.float32).reshape(-1, 4)
    N = sc.shape[0]
    off = 1.0 if pixel_offset else 0.0
    rois_out, scores_out, num_out = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)          # [H*W*A]
        d = bd[n].transpose(1, 2, 0).reshape(-1, 4)
        k = min(int(pre_nms_top_n), s.shape[0])
        top = np.argsort(-s)[:k]
        s_t, d_t, a_t, v_t = s[top], d[top], an[top % an.shape[0]] \
            if an.shape[0] != s.shape[0] else an[top], va[top % va.shape[0]] \
            if va.shape[0] != s.shape[0] else va[top]
        aw = a_t[:, 2] - a_t[:, 0] + off
        ah = a_t[:, 3] - a_t[:, 1] + off
        ax = a_t[:, 0] + aw * 0.5
        ay = a_t[:, 1] + ah * 0.5
        dv = d_t * v_t
        cx = dv[:, 0] * aw + ax
        cy = dv[:, 1] * ah + ay
        bw = np.exp(np.minimum(dv[:, 2], np.log(1000. / 16.))) * aw
        bh = np.exp(np.minimum(dv[:, 3], np.log(1000. / 16.))) * ah
        boxes = np.stack([cx - bw * 0.5, cy - bh * 0.5,
                          cx + bw * 0.5 - off, cy + bh * 0.5 - off], -1)
        h_im, w_im = ims[n, 0], ims[n, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, w_im - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, h_im - off)
        ws = boxes[:, 2] - boxes[:, 0] + off
        hs = boxes[:, 3] - boxes[:, 1] + off
        keep = (ws >= min_size) & (hs >= min_size)
        boxes, s_t = boxes[keep], s_t[keep]
        if boxes.shape[0]:
            kept = nms(Tensor(jnp.asarray(boxes)), nms_thresh,
                       scores=Tensor(jnp.asarray(s_t)),
                       top_k=int(post_nms_top_n))
            kept = np.asarray(kept.numpy())
            boxes, s_t = boxes[kept], s_t[kept]
        rois_out.append(boxes)
        scores_out.append(s_t[:, None])
        num_out.append(boxes.shape[0])
    rois = Tensor(jnp.asarray(np.concatenate(rois_out, 0)
                              if rois_out else np.zeros((0, 4))))
    scr = Tensor(jnp.asarray(np.concatenate(scores_out, 0)
                             if scores_out else np.zeros((0, 1))))
    if return_rois_num:
        return rois, scr, Tensor(jnp.asarray(num_out, jnp.int32))
    return rois, scr, None


def read_file(filename, name=None):
    """Read raw bytes into a uint8 tensor (reference ops.py read_file)."""
    with open(filename, "rb") as f:
        data = f.read()
    import numpy as np
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference ops.py
    decode_jpeg; nvjpeg on GPU — PIL on host here, feeding the device
    tensor)."""
    import io as _io
    import numpy as np
    from PIL import Image
    data = bytes(np.asarray(ensure_tensor(x).numpy(), np.uint8))
    img = Image.open(_io.BytesIO(data))
    if mode.lower() in ("unchanged", "rgb") and img.mode != "RGB":
        img = img.convert("RGB") if mode.lower() == "rgb" else img
    elif mode.lower() in ("gray", "grayscale", "l"):
        img = img.convert("L")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """Classic quantized ROI max pooling (reference ops.py roi_pool,
    roi_pool_kernel): integer bin boundaries, max inside each bin."""
    import numpy as np
    xd = ensure_tensor(x)._data
    bx = ensure_tensor(boxes)._data
    ph, pw = _pair(output_size)
    C, H, W = xd.shape[1], xd.shape[2], xd.shape[3]
    n_num = [int(v) for v in ensure_tensor(boxes_num).numpy()]
    batch_idx = np.repeat(np.arange(len(n_num)), n_num)
    outs = []
    for r in range(bx.shape[0]):
        img = xd[int(batch_idx[r])]
        x1 = jnp.round(bx[r, 0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(bx[r, 1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(bx[r, 2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(bx[r, 3] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rows = []
        ii = jnp.arange(H)[:, None]
        jj = jnp.arange(W)[None, :]
        for i in range(ph):
            cols = []
            for j in range(pw):
                hs = y1 + (i * rh) // ph
                he = y1 + ((i + 1) * rh + ph - 1) // ph
                ws = x1 + (j * rw) // pw
                we = x1 + ((j + 1) * rw + pw - 1) // pw
                m = ((ii >= hs) & (ii < he) & (jj >= ws) & (jj < we))
                neg = jnp.finfo(jnp.float32).min
                vals = jnp.where(m[None], img.astype(jnp.float32),
                                 neg).max((-2, -1))
                empty = (he <= hs) | (we <= ws)
                cols.append(jnp.where(empty, 0.0, vals))
            rows.append(jnp.stack(cols, -1))
        outs.append(jnp.stack(rows, -2).astype(xd.dtype))
    return Tensor(jnp.stack(outs)) if outs else Tensor(
        jnp.zeros((0, C, ph, pw), xd.dtype))


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


def prior_box(input, image, min_sizes, max_sizes=None,
              aspect_ratios=[1.0], variance=[0.1, 0.1, 0.2, 0.2],
              flip=False, clip=False, steps=[0.0, 0.0], offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior (anchor) boxes (reference ops.py:438, prior_box
    kernel). Returns (boxes [H, W, A, 4] in normalized xmin/ymin/xmax/
    ymax, variances of the same shape)."""
    import numpy as np
    feat = ensure_tensor(input)._data
    img = ensure_tensor(image)._data
    fh, fw = int(feat.shape[2]), int(feat.shape[3])
    ih, iw = float(img.shape[2]), float(img.shape[3])
    min_sizes = ([float(m) for m in min_sizes]
                 if isinstance(min_sizes, (list, tuple)) else
                 [float(min_sizes)])
    max_sizes = ([float(m) for m in max_sizes]
                 if isinstance(max_sizes, (list, tuple)) else
                 ([float(max_sizes)] if max_sizes is not None else []))
    ars = [1.0]
    for ar in (aspect_ratios if isinstance(aspect_ratios, (list, tuple))
               else [aspect_ratios]):
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    sh = steps[0] or ih / fh
    sw = steps[1] or iw / fw
    whs = []
    for ms in min_sizes:
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    A = len(whs)
    cy = (np.arange(fh) + offset) * sh
    cx = (np.arange(fw) + offset) * sw
    boxes = np.zeros((fh, fw, A, 4), np.float32)
    for a, (w, h) in enumerate(whs):
        boxes[:, :, a, 0] = (cx[None, :] - w / 2) / iw
        boxes[:, :, a, 1] = (cy[:, None] - h / 2) / ih
        boxes[:, :, a, 2] = (cx[None, :] + w / 2) / iw
        boxes[:, :, a, 3] = (cy[:, None] + h / 2) / ih
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          boxes.shape).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var))


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode a YOLOv3 head (reference ops.py:277, yolo_box kernel):
    x [N, A*(5+C), H, W] -> (boxes [N, H*W*A, 4], scores [N, H*W*A, C]).
    Low-confidence predictions zero out like the kernel."""
    import numpy as np
    xd = ensure_tensor(x)._data.astype(jnp.float32)
    ims = ensure_tensor(img_size)._data.astype(jnp.float32)
    anchors = list(anchors)
    A = len(anchors) // 2
    N, _, H, W = xd.shape
    if iou_aware:
        ious = jax.nn.sigmoid(xd[:, :A].reshape(N, A, 1, H, W))
        xd = xd[:, A:]
    pred = xd.reshape(N, A, 5 + class_num, H, W)
    gx = (jnp.arange(W)[None, :]).astype(jnp.float32)
    gy = (jnp.arange(H)[:, None]).astype(jnp.float32)
    sx = jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y \
        - (scale_x_y - 1) / 2.0
    sy = jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y \
        - (scale_x_y - 1) / 2.0
    bx = (sx + gx) / W
    by = (sy + gy) / H
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    in_w = downsample_ratio * W
    in_h = downsample_ratio * H
    bw = jnp.exp(pred[:, :, 2]) * aw / in_w
    bh = jnp.exp(pred[:, :, 3]) * ah / in_h
    conf = jax.nn.sigmoid(pred[:, :, 4])
    if iou_aware:
        conf = conf ** (1 - iou_aware_factor) * \
            ious[:, :, 0] ** iou_aware_factor
    probs = jax.nn.sigmoid(pred[:, :, 5:]) * conf[:, :, None]
    keep = conf >= conf_thresh
    imh = ims[:, 0][:, None, None, None]
    imw = ims[:, 1][:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0)
        y1 = jnp.clip(y1, 0)
        x2 = jnp.minimum(x2, imw - 1)
        y2 = jnp.minimum(y2, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], -1) * keep[..., None]
    scores = probs * keep[:, :, None]
    # [N, A, H, W, ...] -> [N, H*W*A, ...] (kernel's anchor-major order
    # inside each cell: A varies fastest)
    boxes = boxes.transpose(0, 2, 3, 1, 4).reshape(N, -1, 4)
    scores = scores.transpose(0, 3, 4, 1, 2).reshape(N, -1, class_num)
    return Tensor(boxes), Tensor(scores)


def matrix_nms(bboxes, scores, score_threshold, post_threshold,
               nms_top_k, keep_top_k, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (reference ops.py:2358, SOLOv2): instead of hard
    suppression, each box's score decays by its IoU with higher-scored
    same-class boxes. Host-eager (data-dependent output)."""
    import numpy as np
    bb = np.asarray(ensure_tensor(bboxes).numpy(), np.float32)
    sc = np.asarray(ensure_tensor(scores).numpy(), np.float32)
    N, M = bb.shape[0], bb.shape[1]
    C = sc.shape[1]
    outs, idxs, nums = [], [], []
    for n in range(N):
        dets = []
        for c in range(C):
            if c == background_label:
                continue
            s = sc[n, c]
            sel = np.nonzero(s >= score_threshold)[0]
            if not len(sel):
                continue
            order = sel[np.argsort(-s[sel])][:nms_top_k]
            b = bb[n, order]
            ss = s[order]
            iou = np.asarray(_iou_matrix(jnp.asarray(b), jnp.asarray(b)))
            k = len(order)
            # compensate[j] = max IoU of j with any HIGHER-scored box
            # (strictly above the diagonal — self-IoU must not count)
            comp_all = np.triu(iou, 1).max(axis=0, initial=0)
            decay = np.ones(k, np.float32)
            for i in range(1, k):
                ious_i = iou[:i, i]
                comp = comp_all[:i]
                if use_gaussian:
                    d = np.exp(-(ious_i ** 2 - comp ** 2) / gaussian_sigma)
                else:
                    d = (1 - ious_i) / np.maximum(1 - comp, 1e-9)
                decay[i] = d.min() if len(d) else 1.0
            new_s = ss * decay
            keep = new_s >= post_threshold
            for i in np.nonzero(keep)[0]:
                dets.append((c, float(new_s[i]), b[i], n * M + order[i]))
        dets.sort(key=lambda t: -t[1])
        if keep_top_k > 0:
            dets = dets[:keep_top_k]
        out = np.asarray([[d[0], d[1], *d[2]] for d in dets],
                         np.float32).reshape(-1, 6)
        outs.append(out)
        idxs.append(np.asarray([d[3] for d in dets], np.int64))
        nums.append(len(dets))
    out = Tensor(jnp.asarray(np.concatenate(outs)
                             if outs else np.zeros((0, 6), np.float32)))
    index = Tensor(jnp.asarray(np.concatenate(idxs)
                               if idxs else np.zeros((0,), np.int64)))
    rois_num = Tensor(jnp.asarray(np.asarray(nums, np.int32)))
    res = [out]
    res.append(index if return_index else None)
    res.append(rois_num if return_rois_num else None)
    return tuple(res)


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference ops.py:69, yolo_loss_kernel.cc):
    per gt, the best-IoU anchor's cell owns location (sigmoid-CE for
    x/y, L1 for w/h, scaled by 2-w*h), objectness and class losses;
    predictions overlapping any gt above ignore_thresh are excluded
    from the negative-objectness term. Differentiable jnp expression
    (the gather/scatter of responsible cells replaces the kernel's
    host loops); returns per-sample loss [N]."""
    from ..ops.dispatch import apply_op
    import numpy as np
    tensors = [ensure_tensor(x), ensure_tensor(gt_box),
               ensure_tensor(gt_label)]
    has_score = gt_score is not None
    if has_score:
        tensors.append(ensure_tensor(gt_score))
    anchors = [float(a) for a in anchors]
    mask = [int(m) for m in anchor_mask]

    def sce(logit, label):
        # SigmoidCrossEntropy (yolo_loss_kernel.cc:33)
        return (jnp.maximum(logit, 0.0) - logit * label
                + jnp.log1p(jnp.exp(-jnp.abs(logit))))

    def fn(xd, gtb, gtl, *rest):
        score = (rest[0].astype(jnp.float32) if has_score
                 else jnp.ones(gtb.shape[:2], jnp.float32))
        N, _, H, W = xd.shape
        A = len(mask)
        an_all = len(anchors) // 2
        input_size = downsample_ratio * H
        pred = xd.reshape(N, A, 5 + class_num, H, W).astype(jnp.float32)
        gtb = gtb.astype(jnp.float32)
        B = gtb.shape[1]
        valid = (gtb[..., 2] > 0) & (gtb[..., 3] > 0)        # [N, B]
        sc = float(scale_x_y)
        bias = -0.5 * (sc - 1.0)

        # ---- decoded pred boxes for the ignore mask ----
        gx = jnp.arange(W)[None, :].astype(jnp.float32)
        gy = jnp.arange(H)[:, None].astype(jnp.float32)
        px = (gx + jax.nn.sigmoid(pred[:, :, 0]) * sc + bias) / W
        py = (gy + jax.nn.sigmoid(pred[:, :, 1]) * sc + bias) / H
        aw = jnp.asarray(
            [anchors[2 * m] for m in mask], jnp.float32)[None, :, None,
                                                         None]
        ah = jnp.asarray(
            [anchors[2 * m + 1] for m in mask],
            jnp.float32)[None, :, None, None]
        pw = jnp.exp(pred[:, :, 2]) * aw / input_size
        ph_ = jnp.exp(pred[:, :, 3]) * ah / input_size

        def iou_cwh(x1, y1, w1, h1, x2, y2, w2, h2):
            l = jnp.maximum(x1 - w1 / 2, x2 - w2 / 2)
            r = jnp.minimum(x1 + w1 / 2, x2 + w2 / 2)
            t = jnp.maximum(y1 - h1 / 2, y2 - h2 / 2)
            b = jnp.minimum(y1 + h1 / 2, y2 + h2 / 2)
            inter = jnp.clip(r - l, 0) * jnp.clip(b - t, 0)
            return inter / jnp.maximum(w1 * h1 + w2 * h2 - inter, 1e-9)

        # [N, A, H, W, B] IoU of each pred with each gt
        ious = iou_cwh(px[..., None], py[..., None], pw[..., None],
                       ph_[..., None],
                       gtb[:, None, None, None, :, 0],
                       gtb[:, None, None, None, :, 1],
                       gtb[:, None, None, None, :, 2],
                       gtb[:, None, None, None, :, 3])
        ious = jnp.where(valid[:, None, None, None, :], ious, 0.0)
        best_iou = ious.max(-1)                             # [N, A, H, W]
        ignore = best_iou > ignore_thresh

        # ---- per-gt responsible anchor/cell ----
        an_w = jnp.asarray(anchors[0::2], jnp.float32) / input_size
        an_h = jnp.asarray(anchors[1::2], jnp.float32) / input_size
        gt_w = gtb[..., 2][..., None]                       # [N, B, 1]
        gt_h = gtb[..., 3][..., None]
        inter = (jnp.minimum(gt_w, an_w[None, None])
                 * jnp.minimum(gt_h, an_h[None, None]))
        an_iou = inter / jnp.maximum(
            gt_w * gt_h + an_w[None, None] * an_h[None, None] - inter,
            1e-9)
        best_n = jnp.argmax(an_iou, axis=-1)                # [N, B]
        mask_arr = jnp.asarray(mask)
        in_mask = (best_n[..., None] == mask_arr[None, None]).any(-1)
        mask_idx = jnp.argmax(
            best_n[..., None] == mask_arr[None, None], axis=-1)
        gi = jnp.clip((gtb[..., 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gtb[..., 1] * H).astype(jnp.int32), 0, H - 1)
        resp = valid & in_mask                              # [N, B]
        w_s = (2.0 - gtb[..., 2] * gtb[..., 3]) * score     # box scale

        # gather the responsible cell's predictions per gt: [N, B, 5+C]
        bidx = jnp.arange(N)[:, None]
        cell = pred[bidx, mask_idx, :, gj, gi]
        tx = gtb[..., 0] * W - gi
        ty = gtb[..., 1] * H - gj
        tw = jnp.log(jnp.maximum(
            gtb[..., 2] * input_size
            / jnp.take(jnp.asarray(anchors[0::2]), best_n), 1e-9))
        th = jnp.log(jnp.maximum(
            gtb[..., 3] * input_size
            / jnp.take(jnp.asarray(anchors[1::2]), best_n), 1e-9))
        loc = (sce(cell[..., 0], tx) + sce(cell[..., 1], ty)
               + jnp.abs(cell[..., 2] - tw)
               + jnp.abs(cell[..., 3] - th)) * w_s
        smooth = min(1.0 / class_num, 1.0 / 40) if use_label_smooth \
            else 0.0
        pos, neg = 1.0 - smooth, smooth
        onehot = jax.nn.one_hot(gtl.astype(jnp.int32), class_num)
        cls_tgt = onehot * pos + (1 - onehot) * neg
        cls = jnp.sum(sce(cell[..., 5:], cls_tgt), -1) * score
        per_gt = jnp.where(resp, loc + cls, 0.0)

        # objectness: positive at responsible cells (score), negative
        # elsewhere unless ignored
        obj_pos = jnp.zeros((N, A, H, W), jnp.float32)
        obj_pos = obj_pos.at[bidx, mask_idx, gj, gi].add(
            jnp.where(resp, score, 0.0))
        is_pos = jnp.zeros((N, A, H, W), bool)
        is_pos = is_pos.at[bidx, mask_idx, gj, gi].max(resp)
        obj_logit = pred[:, :, 4]
        pos_loss = sce(obj_logit, 1.0) * obj_pos
        neg_loss = jnp.where(~is_pos & ~ignore,
                             sce(obj_logit, 0.0), 0.0)
        return (per_gt.sum(-1)
                + (pos_loss + neg_loss).sum((-3, -2, -1)))

    return apply_op("yolo_loss", fn, tuple(tensors), {})
