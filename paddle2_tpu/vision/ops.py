"""paddle.vision.ops (reference python/paddle/vision/ops.py; the detection
primitives re-expressed in jnp — nms runs as an XLA while-loop-free
mask-matrix algorithm instead of the reference's CUDA kernel)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import ensure_tensor

__all__ = ["nms", "box_area", "box_iou", "roi_align", "RoIAlign"]


def box_area(boxes):
    b = ensure_tensor(boxes)._data
    return Tensor((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def _iou_matrix(a, b):
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-9)


def box_iou(boxes1, boxes2):
    a = ensure_tensor(boxes1)._data
    b = ensure_tensor(boxes2)._data
    return Tensor(_iou_matrix(a, b))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """ops.py nms parity. Returns kept indices sorted by descending score.

    Greedy NMS as a numpy loop on host (data-dependent output size cannot
    trace; the reference's GPU kernel is also a sequential bitmask scan).
    """
    import numpy as np
    b = np.asarray(ensure_tensor(boxes)._data)
    n = b.shape[0]
    s = (np.asarray(ensure_tensor(scores)._data) if scores is not None
         else np.arange(n, 0, -1, dtype="float32"))
    cats = (np.asarray(ensure_tensor(category_idxs)._data)
            if category_idxs is not None else np.zeros(n, "int64"))
    iou = np.asarray(_iou_matrix(jnp.asarray(b), jnp.asarray(b)))
    order = np.argsort(-s)
    keep, suppressed = [], np.zeros(n, bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        overlap = (iou[i] > iou_threshold) & (cats == cats[i])
        suppressed |= overlap
        suppressed[i] = True
    keep = np.asarray(keep, "int64")
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """ops.py roi_align parity (average-pool variant via bilinear grid
    sampling with jnp gathers)."""
    import numpy as np
    xd = ensure_tensor(x)._data
    bx = ensure_tensor(boxes)._data
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    n_num = [int(v) for v in ensure_tensor(boxes_num).numpy()]
    batch_idx = np.repeat(np.arange(len(n_num)), n_num)

    offset = 0.5 if aligned else 0.0
    C = xd.shape[1]
    H, W = xd.shape[2], xd.shape[3]
    outs = []
    ratio = sampling_ratio if sampling_ratio > 0 else 2
    for r in range(bx.shape[0]):
        b = batch_idx[r]
        x1, y1, x2, y2 = [bx[r, i] * spatial_scale - offset for i in range(4)]
        rh = jnp.maximum(y2 - y1, 1e-3) / ph
        rw = jnp.maximum(x2 - x1, 1e-3) / pw
        iy = (jnp.arange(ph)[:, None] + (jnp.arange(ratio) + 0.5)[None, :]
              / ratio).reshape(-1)
        ix = (jnp.arange(pw)[:, None] + (jnp.arange(ratio) + 0.5)[None, :]
              / ratio).reshape(-1)
        ys = y1 + iy * rh                      # (ph*ratio,)
        xs = x1 + ix * rw                      # (pw*ratio,)
        y0 = jnp.clip(jnp.floor(ys), 0, H - 1).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(xs), 0, W - 1).astype(jnp.int32)
        y1i = jnp.clip(y0 + 1, 0, H - 1)
        x1i = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(ys, 0, H - 1) - y0
        wx = jnp.clip(xs, 0, W - 1) - x0
        img = xd[b]                            # (C, H, W)
        v00 = img[:, y0][:, :, x0]
        v01 = img[:, y0][:, :, x1i]
        v10 = img[:, y1i][:, :, x0]
        v11 = img[:, y1i][:, :, x1i]
        val = (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
               + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
               + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
               + v11 * wy[None, :, None] * wx[None, None, :])
        val = val.reshape(C, ph, ratio, pw, ratio).mean(axis=(2, 4))
        outs.append(val)
    return Tensor(jnp.stack(outs)) if outs else Tensor(
        jnp.zeros((0, C, ph, pw), xd.dtype))


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)
