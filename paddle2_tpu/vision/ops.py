"""paddle.vision.ops (reference python/paddle/vision/ops.py; the detection
primitives re-expressed in jnp — nms runs as an XLA while-loop-free
mask-matrix algorithm instead of the reference's CUDA kernel)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..ops.dispatch import ensure_tensor

__all__ = ["nms", "box_area", "box_iou", "roi_align", "RoIAlign",
           "deform_conv2d", "DeformConv2D", "psroi_pool", "PSRoIPool",
           "box_coder", "distribute_fpn_proposals", "generate_proposals",
           "read_file", "decode_jpeg"]


def box_area(boxes):
    b = ensure_tensor(boxes)._data
    return Tensor((b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]))


def _iou_matrix(a, b):
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-9)


def box_iou(boxes1, boxes2):
    a = ensure_tensor(boxes1)._data
    b = ensure_tensor(boxes2)._data
    return Tensor(_iou_matrix(a, b))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """ops.py nms parity. Returns kept indices sorted by descending score.

    Greedy NMS as a numpy loop on host (data-dependent output size cannot
    trace; the reference's GPU kernel is also a sequential bitmask scan).
    """
    import numpy as np
    b = np.asarray(ensure_tensor(boxes)._data)
    n = b.shape[0]
    s = (np.asarray(ensure_tensor(scores)._data) if scores is not None
         else np.arange(n, 0, -1, dtype="float32"))
    cats = (np.asarray(ensure_tensor(category_idxs)._data)
            if category_idxs is not None else np.zeros(n, "int64"))
    iou = np.asarray(_iou_matrix(jnp.asarray(b), jnp.asarray(b)))
    order = np.argsort(-s)
    keep, suppressed = [], np.zeros(n, bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        overlap = (iou[i] > iou_threshold) & (cats == cats[i])
        suppressed |= overlap
        suppressed[i] = True
    keep = np.asarray(keep, "int64")
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """ops.py roi_align parity (average-pool variant via bilinear grid
    sampling with jnp gathers)."""
    import numpy as np
    xd = ensure_tensor(x)._data
    bx = ensure_tensor(boxes)._data
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    n_num = [int(v) for v in ensure_tensor(boxes_num).numpy()]
    batch_idx = np.repeat(np.arange(len(n_num)), n_num)

    offset = 0.5 if aligned else 0.0
    C = xd.shape[1]
    H, W = xd.shape[2], xd.shape[3]
    outs = []
    ratio = sampling_ratio if sampling_ratio > 0 else 2
    for r in range(bx.shape[0]):
        b = batch_idx[r]
        x1, y1, x2, y2 = [bx[r, i] * spatial_scale - offset for i in range(4)]
        rh = jnp.maximum(y2 - y1, 1e-3) / ph
        rw = jnp.maximum(x2 - x1, 1e-3) / pw
        iy = (jnp.arange(ph)[:, None] + (jnp.arange(ratio) + 0.5)[None, :]
              / ratio).reshape(-1)
        ix = (jnp.arange(pw)[:, None] + (jnp.arange(ratio) + 0.5)[None, :]
              / ratio).reshape(-1)
        ys = y1 + iy * rh                      # (ph*ratio,)
        xs = x1 + ix * rw                      # (pw*ratio,)
        y0 = jnp.clip(jnp.floor(ys), 0, H - 1).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(xs), 0, W - 1).astype(jnp.int32)
        y1i = jnp.clip(y0 + 1, 0, H - 1)
        x1i = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(ys, 0, H - 1) - y0
        wx = jnp.clip(xs, 0, W - 1) - x0
        img = xd[b]                            # (C, H, W)
        v00 = img[:, y0][:, :, x0]
        v01 = img[:, y0][:, :, x1i]
        v10 = img[:, y1i][:, :, x0]
        v11 = img[:, y1i][:, :, x1i]
        val = (v00 * (1 - wy)[None, :, None] * (1 - wx)[None, None, :]
               + v01 * (1 - wy)[None, :, None] * wx[None, None, :]
               + v10 * wy[None, :, None] * (1 - wx)[None, None, :]
               + v11 * wy[None, :, None] * wx[None, None, :])
        val = val.reshape(C, ph, ratio, pw, ratio).mean(axis=(2, 4))
        outs.append(val)
    return Tensor(jnp.stack(outs)) if outs else Tensor(
        jnp.zeros((0, C, ph, pw), xd.dtype))


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _bilinear_sample(img, ys, xs):
    """Zero-padded bilinear sampling. img [C, H, W]; ys/xs any shape S.
    Returns [C, *S]. Out-of-bounds corners contribute zero (the
    deformable-conv border convention, deformable_conv_kernel.cu)."""
    H, W = img.shape[-2:]
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    wy = ys - y0
    wx = xs - x0

    def corner(yc, xc, w):
        valid = (yc >= 0) & (yc < H) & (xc >= 0) & (xc < W)
        v = img[:, jnp.clip(yc, 0, H - 1), jnp.clip(xc, 0, W - 1)]
        return v * (w * valid)[None]

    return (corner(y0, x0, (1 - wy) * (1 - wx))
            + corner(y0, x0 + 1, (1 - wy) * wx)
            + corner(y0 + 1, x0, wy * (1 - wx))
            + corner(y0 + 1, x0 + 1, wy * wx))


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference ops.py:766; CUDA kernel
    deformable_conv_kernel). Each kernel tap samples the input at its
    grid position plus a learned offset (bilinear), optionally scaled by
    a modulation mask (v2), then contracts with the weights — expressed
    here as gather-based sampling + one einsum so XLA fuses it and the
    tape differentiates it."""
    from ..ops.dispatch import apply_op

    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)
    tensors = [ensure_tensor(x), ensure_tensor(offset),
               ensure_tensor(weight)]
    has_mask = mask is not None
    has_bias = bias is not None
    if has_mask:
        tensors.append(ensure_tensor(mask))
    if has_bias:
        tensors.append(ensure_tensor(bias))

    def fn(xd, od, wd, *rest):
        md = rest[0] if has_mask else None
        bd = rest[-1] if has_bias else None
        N, Cin, H, W = xd.shape
        Cout, Cin_g, kh, kw = wd.shape
        K = kh * kw
        dg = deformable_groups
        Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        # base sampling grid per tap
        ys0 = (jnp.arange(Ho) * sh - ph)[None, :, None] \
            + (jnp.arange(kh) * dh).repeat(kw)[:, None, None]
        xs0 = (jnp.arange(Wo) * sw - pw)[None, None, :] \
            + jnp.tile(jnp.arange(kw) * dw, kh)[:, None, None]
        off = od.reshape(N, dg, K, 2, Ho, Wo)
        ys = ys0[None, None] + off[:, :, :, 0]        # [N, dg, K, Ho, Wo]
        xs = xs0[None, None] + off[:, :, :, 1]
        xg = xd.reshape(N, dg, Cin // dg, H, W)

        samp = jax.vmap(jax.vmap(_bilinear_sample))(xg, ys, xs)
        # [N, dg, C/dg, K, Ho, Wo]
        if md is not None:
            samp = samp * md.reshape(N, dg, 1, K, Ho, Wo)
        samp = samp.reshape(N, groups, Cin // groups, K, Ho, Wo)
        wg = wd.reshape(groups, Cout // groups, Cin_g, K)
        out = jnp.einsum("gock,ngckij->ngoij", wg, samp,
                         preferred_element_type=jnp.float32)
        out = out.reshape(N, Cout, Ho, Wo).astype(xd.dtype)
        if bd is not None:
            out = out + bd[None, :, None, None]
        return out

    return apply_op("deform_conv2d", fn, tuple(tensors), {})


def _layer_base():
    from ..nn import Layer
    return Layer


class DeformConv2D(_layer_base()):
    """Layer form of deform_conv2d (reference ops.py DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1,
                 deformable_groups=1, groups=1, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        kh, kw = _pair(kernel_size)
        self._attrs = (stride, padding, dilation,
                       deformable_groups, groups)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr)
        self.bias = None if bias_attr is False else \
            self.create_parameter([out_channels], attr=bias_attr,
                                  is_bias=True)

    def forward(self, x, offset, mask=None):
        s, p, d, dg, g = self._attrs
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=s, padding=p, dilation=d,
                             deformable_groups=dg, groups=g, mask=mask)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive ROI pooling (reference ops.py:1441,
    psroi_pool_kernel): input channels C = out_c * ph * pw; output bin
    (i, j) of channel c average-pools its DEDICATED input channel
    c*ph*pw + i*pw + j over the bin's region."""
    import numpy as np
    xd = ensure_tensor(x)._data
    bx = ensure_tensor(boxes)._data
    ph, pw = _pair(output_size)
    C = xd.shape[1]
    if C % (ph * pw) != 0:
        raise ValueError(
            f"psroi_pool input channels {C} must be divisible by "
            f"output_size {ph}x{pw}")
    out_c = C // (ph * pw)
    H, W = xd.shape[2], xd.shape[3]
    n_num = [int(v) for v in ensure_tensor(boxes_num).numpy()]
    batch_idx = np.repeat(np.arange(len(n_num)), n_num)
    outs = []
    for r in range(bx.shape[0]):
        img = xd[int(batch_idx[r])]  # [C, H, W]
        x1, y1, x2, y2 = [bx[r, i] * spatial_scale for i in range(4)]
        bin_h = (y2 - y1) / ph
        bin_w = (x2 - x1) / pw
        chans = jnp.arange(out_c * ph * pw).reshape(out_c, ph, pw)
        rows = []
        for i in range(ph):
            cols = []
            for j in range(pw):
                hs = jnp.clip(jnp.floor(y1 + i * bin_h), 0, H).astype(int)
                he = jnp.clip(jnp.ceil(y1 + (i + 1) * bin_h), 0, H).astype(int)
                ws = jnp.clip(jnp.floor(x1 + j * bin_w), 0, W).astype(int)
                we = jnp.clip(jnp.ceil(x1 + (j + 1) * bin_w), 0, W).astype(int)
                # dynamic extents: mask-average instead of slicing
                ii = jnp.arange(H)[:, None]
                jj = jnp.arange(W)[None, :]
                m = ((ii >= hs) & (ii < he) & (jj >= ws) & (jj < we))
                area = jnp.maximum(m.sum(), 1)
                vals = (img[chans[:, i, j]] * m[None]).sum((-2, -1)) / area
                empty = (he <= hs) | (we <= ws)
                cols.append(jnp.where(empty, 0.0, vals))
            rows.append(jnp.stack(cols, -1))
        outs.append(jnp.stack(rows, -2))  # [out_c, ph, pw]
    return Tensor(jnp.stack(outs)) if outs else Tensor(
        jnp.zeros((0, out_c, ph, pw), xd.dtype))


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode target boxes against prior (anchor) boxes
    (reference ops.py:584, phi box_coder kernel)."""
    pb = ensure_tensor(prior_box)._data.astype(jnp.float32)
    tb = ensure_tensor(target_box)._data.astype(jnp.float32)
    if isinstance(prior_box_var, (list, tuple)):
        pbv = jnp.asarray(prior_box_var, jnp.float32)
    elif prior_box_var is None:
        pbv = jnp.ones((4,), jnp.float32)
    else:
        pbv = ensure_tensor(prior_box_var)._data.astype(jnp.float32)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    ph = pb[:, 3] - pb[:, 1] + norm
    px = pb[:, 0] + pw * 0.5
    py = pb[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        # tb [N, 4] targets vs priors [M, 4] -> [N, M, 4] (the kernel's
        # row = target, col = prior orientation, box_coder kernel)
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tx = tb[:, 0] + tw * 0.5
        ty = tb[:, 1] + th * 0.5
        ox = (tx[:, None] - px[None, :]) / pw[None, :]
        oy = (ty[:, None] - py[None, :]) / ph[None, :]
        ow = jnp.log(tw[:, None] / pw[None, :])
        oh = jnp.log(th[:, None] / ph[None, :])
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
        out = out / (pbv.reshape(-1, 4)[None, :] if pbv.ndim == 2
                     else pbv[None, None])
        return Tensor(out)
    if code_type != "decode_center_size":
        raise ValueError(f"unknown code_type {code_type!r}")
    # decode: tb [N, M, 4] deltas; priors broadcast ALONG `axis` (axis=0:
    # PriorBox [M, 4] tiles over dim 0, i.e. priors vary on dim 1)
    if tb.ndim == 2:
        tb = tb[:, None]
    if axis == 0:
        px_, py_, pw_, ph_ = (px[None, :], py[None, :],
                              pw[None, :], ph[None, :])
        var = pbv.reshape(-1, 4)[None, :] if pbv.ndim == 2 \
            else pbv[None, None]
    else:
        px_, py_, pw_, ph_ = (px[:, None], py[:, None],
                              pw[:, None], ph[:, None])
        var = pbv.reshape(-1, 4)[:, None] if pbv.ndim == 2 \
            else pbv[None, None]
    d = tb * var
    ox = d[..., 0] * pw_ + px_
    oy = d[..., 1] * ph_ + py_
    ow = jnp.exp(d[..., 2]) * pw_
    oh = jnp.exp(d[..., 3]) * ph_
    out = jnp.stack([ox - ow * 0.5, oy - oh * 0.5,
                     ox + ow * 0.5 - norm, oy + oh * 0.5 - norm],
                    axis=-1)
    return Tensor(out)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False,
                             rois_num=None, name=None):
    """Route each ROI to its FPN level by scale (reference ops.py:1200):
    level = floor(refer_level + log2(sqrt(area) / refer_scale)), clipped
    to [min_level, max_level]. Output sizes are data-dependent, so this
    runs eagerly on host values (the reference's is a CPU/GPU kernel with
    dynamic outputs for the same reason)."""
    import numpy as np
    rois = np.asarray(ensure_tensor(fpn_rois).numpy(), np.float32)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8) + refer_level)
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    num_levels = max_level - min_level + 1
    multi_rois, restore_parts, rois_num_per_level = [], [], []
    for i in range(num_levels):
        idx = np.nonzero(lvl == min_level + i)[0]
        multi_rois.append(Tensor(jnp.asarray(rois[idx])))
        restore_parts.append(idx)
        rois_num_per_level.append(Tensor(jnp.asarray([len(idx)],
                                                     jnp.int32)))
    order = np.concatenate(restore_parts) if restore_parts else \
        np.zeros((0,), np.int64)
    restore_ind = np.empty_like(order)
    restore_ind[order] = np.arange(len(order))
    restore = Tensor(jnp.asarray(restore_ind.reshape(-1, 1), jnp.int32))
    if rois_num is not None:
        return multi_rois, restore, rois_num_per_level
    return multi_rois, restore, None


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference ops.py:2159, phi
    generate_proposals kernel): per image, top-k anchors by score ->
    decode deltas -> clip to image -> drop tiny boxes -> NMS -> top-k.
    Output counts are data-dependent: host-eager like the reference's
    kernel launch + dynamic output."""
    import numpy as np
    sc = np.asarray(ensure_tensor(scores).numpy(), np.float32)
    bd = np.asarray(ensure_tensor(bbox_deltas).numpy(), np.float32)
    ims = np.asarray(ensure_tensor(img_size).numpy(), np.float32)
    an = np.asarray(ensure_tensor(anchors).numpy(),
                    np.float32).reshape(-1, 4)
    va = np.asarray(ensure_tensor(variances).numpy(),
                    np.float32).reshape(-1, 4)
    N = sc.shape[0]
    off = 1.0 if pixel_offset else 0.0
    rois_out, scores_out, num_out = [], [], []
    for n in range(N):
        s = sc[n].transpose(1, 2, 0).reshape(-1)          # [H*W*A]
        d = bd[n].transpose(1, 2, 0).reshape(-1, 4)
        k = min(int(pre_nms_top_n), s.shape[0])
        top = np.argsort(-s)[:k]
        s_t, d_t, a_t, v_t = s[top], d[top], an[top % an.shape[0]] \
            if an.shape[0] != s.shape[0] else an[top], va[top % va.shape[0]] \
            if va.shape[0] != s.shape[0] else va[top]
        aw = a_t[:, 2] - a_t[:, 0] + off
        ah = a_t[:, 3] - a_t[:, 1] + off
        ax = a_t[:, 0] + aw * 0.5
        ay = a_t[:, 1] + ah * 0.5
        dv = d_t * v_t
        cx = dv[:, 0] * aw + ax
        cy = dv[:, 1] * ah + ay
        bw = np.exp(np.minimum(dv[:, 2], np.log(1000. / 16.))) * aw
        bh = np.exp(np.minimum(dv[:, 3], np.log(1000. / 16.))) * ah
        boxes = np.stack([cx - bw * 0.5, cy - bh * 0.5,
                          cx + bw * 0.5 - off, cy + bh * 0.5 - off], -1)
        h_im, w_im = ims[n, 0], ims[n, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, w_im - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, h_im - off)
        ws = boxes[:, 2] - boxes[:, 0] + off
        hs = boxes[:, 3] - boxes[:, 1] + off
        keep = (ws >= min_size) & (hs >= min_size)
        boxes, s_t = boxes[keep], s_t[keep]
        if boxes.shape[0]:
            kept = nms(Tensor(jnp.asarray(boxes)), nms_thresh,
                       scores=Tensor(jnp.asarray(s_t)),
                       top_k=int(post_nms_top_n))
            kept = np.asarray(kept.numpy())
            boxes, s_t = boxes[kept], s_t[kept]
        rois_out.append(boxes)
        scores_out.append(s_t[:, None])
        num_out.append(boxes.shape[0])
    rois = Tensor(jnp.asarray(np.concatenate(rois_out, 0)
                              if rois_out else np.zeros((0, 4))))
    scr = Tensor(jnp.asarray(np.concatenate(scores_out, 0)
                             if scores_out else np.zeros((0, 1))))
    if return_rois_num:
        return rois, scr, Tensor(jnp.asarray(num_out, jnp.int32))
    return rois, scr, None


def read_file(filename, name=None):
    """Read raw bytes into a uint8 tensor (reference ops.py read_file)."""
    with open(filename, "rb") as f:
        data = f.read()
    import numpy as np
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference ops.py
    decode_jpeg; nvjpeg on GPU — PIL on host here, feeding the device
    tensor)."""
    import io as _io
    import numpy as np
    from PIL import Image
    data = bytes(np.asarray(ensure_tensor(x).numpy(), np.uint8))
    img = Image.open(_io.BytesIO(data))
    if mode.lower() in ("unchanged", "rgb") and img.mode != "RGB":
        img = img.convert("RGB") if mode.lower() == "rgb" else img
    elif mode.lower() in ("gray", "grayscale", "l"):
        img = img.convert("L")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))
