"""paddle.vision.transforms (reference
python/paddle/vision/transforms/transforms.py; independent implementation).

Transforms are HOST-side preprocessing (numpy/PIL): on TPU the accelerator
should spend its cycles on the model, and the DataLoader's prefetcher
overlaps this work with device steps.
"""

from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

from . import functional  # noqa: F401
from . import functional as F

__all__ = ["BaseTransform", "Compose", "ToTensor", "Normalize", "Resize",
           "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose",
           "RandomCrop", "CenterCrop", "RandomResizedCrop", "Pad",
           "Grayscale", "BrightnessTransform", "ContrastTransform",
           "RandomRotation", "functional"]


class BaseTransform:
    """transforms.py BaseTransform parity (single-input form)."""

    def __init__(self, keys=None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            return (self._apply_image(inputs[0]),) + inputs[1:]
        return self._apply_image(inputs)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = F._to_numpy(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        arr_h, arr_w = F._to_numpy(img).shape[:2]
        th, tw = self.size
        if self.pad_if_needed and arr_h < th:
            img = F.pad(img, (0, th - arr_h), self.fill, self.padding_mode)
            arr_h = th
        if self.pad_if_needed and arr_w < tw:
            img = F.pad(img, (tw - arr_w, 0), self.fill, self.padding_mode)
            arr_w = tw
        top = random.randint(0, arr_h - th)
        left = random.randint(0, arr_w - tw)
        return F.crop(img, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr_h, arr_w = F._to_numpy(img).shape[:2]
        area = arr_h * arr_w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= arr_w and 0 < h <= arr_h:
                top = random.randint(0, arr_h - h)
                left = random.randint(0, arr_w - w)
                img = F.crop(img, top, left, h, w)
                return F.resize(img, self.size, self.interpolation)
        return F.resize(F.center_crop(img, min(arr_h, arr_w)), self.size,
                        self.interpolation)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_brightness(img, factor)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return F.adjust_contrast(img, factor)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)
