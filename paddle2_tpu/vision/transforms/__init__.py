"""paddle.vision.transforms (reference
python/paddle/vision/transforms/transforms.py; independent implementation).

Transforms are HOST-side preprocessing (numpy/PIL): on TPU the accelerator
should spend its cycles on the model, and the DataLoader's prefetcher
overlaps this work with device steps.
"""

from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

from . import functional  # noqa: F401
from . import functional as F

__all__ = ["BaseTransform", "Compose", "ToTensor", "Normalize", "Resize",
           "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose",
           "RandomCrop", "CenterCrop", "RandomResizedCrop", "Pad",
           "Grayscale", "BrightnessTransform", "ContrastTransform",
           "RandomRotation", "functional", "SaturationTransform",
           "HueTransform", "ColorJitter", "RandomErasing", "RandomAffine",
           "RandomPerspective",
           # functional re-exports (reference exports them at this level)
           "to_tensor", "normalize", "resize", "crop", "center_crop",
           "hflip", "vflip", "pad", "rotate", "to_grayscale",
           "adjust_brightness", "adjust_contrast", "adjust_hue",
           "affine", "perspective", "erase"]

from .functional import (adjust_brightness, adjust_contrast,  # noqa: F401,E402
                         adjust_hue, affine, center_crop, crop, erase,
                         hflip, normalize, pad, perspective, resize,
                         rotate, to_grayscale, to_tensor, vflip)


class BaseTransform:
    """transforms.py BaseTransform parity (single-input form)."""

    def __init__(self, keys=None):
        self.keys = keys

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            return (self._apply_image(inputs[0]),) + inputs[1:]
        return self._apply_image(inputs)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return F.to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return F.normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return F.resize(img, self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return F.vflip(img) if random.random() < self.prob else img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = F._to_numpy(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return np.transpose(arr, self.order)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return F.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        if self.padding is not None:
            img = F.pad(img, self.padding, self.fill, self.padding_mode)
        arr_h, arr_w = F._to_numpy(img).shape[:2]
        th, tw = self.size
        if self.pad_if_needed and arr_h < th:
            img = F.pad(img, (0, th - arr_h), self.fill, self.padding_mode)
            arr_h = th
        if self.pad_if_needed and arr_w < tw:
            img = F.pad(img, (tw - arr_w, 0), self.fill, self.padding_mode)
            arr_w = tw
        top = random.randint(0, arr_h - th)
        left = random.randint(0, arr_w - tw)
        return F.crop(img, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr_h, arr_w = F._to_numpy(img).shape[:2]
        area = arr_h * arr_w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= arr_w and 0 < h <= arr_h:
                top = random.randint(0, arr_h - h)
                left = random.randint(0, arr_w - w)
                img = F.crop(img, top, left, h, w)
                return F.resize(img, self.size, self.interpolation)
        return F.resize(F.center_crop(img, min(arr_h, arr_w)), self.size,
                        self.interpolation)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return F.pad(img, self.padding, self.fill, self.padding_mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return F.to_grayscale(img, self.num_output_channels)


def _jitter_range(value, center=1.0):
    """Scalar v -> [max(0, c-v), c+v]; (lo, hi) passes through
    (reference ColorJitter _check_input)."""
    if isinstance(value, (tuple, list)):
        return float(value[0]), float(value[1])
    v = float(value)
    return max(0.0, center - v), center + v


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = _jitter_range(value)

    def _apply_image(self, img):
        lo, hi = self.value
        if lo == hi == 1.0:
            return img
        return F.adjust_brightness(img, random.uniform(lo, hi))


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = _jitter_range(value)

    def _apply_image(self, img):
        lo, hi = self.value
        if lo == hi == 1.0:
            return img
        return F.adjust_contrast(img, random.uniform(lo, hi))


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return F.rotate(img, angle, self.interpolation, self.expand,
                        self.center, self.fill)


class SaturationTransform(BaseTransform):
    """transforms.py SaturationTransform."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = _jitter_range(value)

    def _apply_image(self, img):
        lo, hi = self.value
        if lo == hi == 1.0:
            return img
        return F.adjust_saturation(img, random.uniform(lo, hi))


class HueTransform(BaseTransform):
    """transforms.py HueTransform (scalar in [0, 0.5] or (lo, hi))."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        if isinstance(value, (tuple, list)):
            lo, hi = float(value[0]), float(value[1])
        else:
            if not 0 <= value <= 0.5:
                raise ValueError("hue value must be in [0, 0.5]")
            lo, hi = -float(value), float(value)
        if not -0.5 <= lo <= hi <= 0.5:
            raise ValueError("hue range must lie in [-0.5, 0.5]")
        self.value = (lo, hi)

    def _apply_image(self, img):
        lo, hi = self.value
        if lo == hi == 0.0:
            return img
        return F.adjust_hue(img, random.uniform(lo, hi))


class ColorJitter(BaseTransform):
    """transforms.py ColorJitter: random brightness/contrast/saturation/
    hue, applied in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(self.transforms)
        random.shuffle(order)
        for t in order:
            img = t._apply_image(img)
        return img


class RandomErasing(BaseTransform):
    """transforms.py RandomErasing (Zhong et al.): erase a random
    rectangle with probability `prob`."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _apply_image(self, img):
        import numpy as np
        if random.random() >= self.prob:
            return img
        arr = F._to_numpy(img)
        H, W = arr.shape[:2]
        from ...framework.tensor import Tensor
        if isinstance(img, Tensor) and img.ndim == 3:
            H, W = img.shape[-2], img.shape[-1]
        area = H * W
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            h = int(round(np.sqrt(target * ar)))
            w = int(round(np.sqrt(target / ar)))
            if h < H and w < W:
                i = random.randint(0, H - h)
                j = random.randint(0, W - w)
                v = (random.random() if self.value == "random"
                     else self.value)
                return F.erase(img, i, j, h, w, v, inplace=self.inplace)
        return img


class RandomAffine(BaseTransform):
    """transforms.py RandomAffine: random rotation/translate/scale/shear."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = ((-degrees, degrees)
                        if isinstance(degrees, (int, float)) else degrees)
        self.translate, self.scale_rng = translate, scale
        self.shear = shear
        self.interpolation, self.fill, self.center = (interpolation, fill,
                                                      center)

    def _apply_image(self, img):
        import numpy as np
        angle = random.uniform(*self.degrees)
        H, W = F._to_numpy(img).shape[:2]
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * W
            ty = random.uniform(-self.translate[1], self.translate[1]) * H
        else:
            tx = ty = 0.0
        sc = (random.uniform(*self.scale_rng)
              if self.scale_rng is not None else 1.0)
        if self.shear is None:
            sh = (0.0, 0.0)
        elif isinstance(self.shear, (int, float)):
            sh = (random.uniform(-self.shear, self.shear), 0.0)
        elif len(self.shear) == 4:
            # (x_min, x_max, y_min, y_max) — reference 4-tuple form
            sh = (random.uniform(self.shear[0], self.shear[1]),
                  random.uniform(self.shear[2], self.shear[3]))
        else:
            sh = (random.uniform(self.shear[0], self.shear[1]), 0.0)
        return F.affine(img, angle, (tx, ty), sc, sh,
                        interpolation=self.interpolation, fill=self.fill,
                        center=self.center)


class RandomPerspective(BaseTransform):
    """transforms.py RandomPerspective: random 4-point projective warp
    with probability `prob`."""

    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation, self.fill = interpolation, fill

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        H, W = F._to_numpy(img).shape[:2]
        d = self.distortion_scale
        hw = int(W * d / 2)
        hh = int(H * d / 2)

        def jig(x, y):
            return (x + random.randint(-hw, hw) if hw else x,
                    y + random.randint(-hh, hh) if hh else y)

        start = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
        end = [jig(*p) for p in start]
        return F.perspective(img, start, end,
                             interpolation=self.interpolation,
                             fill=self.fill)
