"""Functional image transforms (reference
python/paddle/vision/transforms/functional.py; independent numpy/PIL
implementation — TPU note: transforms are host-side data prep, so they stay
in numpy/PIL and never trace)."""

from __future__ import annotations

import numbers
from typing import Sequence

import numpy as np

try:
    from PIL import Image
    _HAS_PIL = True
except ImportError:  # pragma: no cover
    _HAS_PIL = False


def _is_pil(img):
    return _HAS_PIL and isinstance(img, Image.Image)


def _to_numpy(img) -> np.ndarray:
    """HWC uint8/float numpy view of a PIL image / ndarray / Tensor."""
    if _is_pil(img):
        return np.asarray(img)
    from ...framework.tensor import Tensor
    if isinstance(img, Tensor):
        return np.asarray(img.numpy())
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    """functional.py to_tensor: HWC [0,255] -> CHW float32 [0,1] Tensor."""
    from ... import to_tensor as paddle_to_tensor
    arr = _to_numpy(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.dtype == np.uint8:
        arr = arr.astype("float32") / 255.0
    else:
        arr = arr.astype("float32")
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return paddle_to_tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _to_numpy(img).astype("float32")
    mean = np.asarray(mean, dtype="float32")
    std = np.asarray(std, dtype="float32")
    if data_format == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    return (arr - mean.reshape(shape)) / std.reshape(shape)


def resize(img, size, interpolation="bilinear"):
    """size: int (short side) or (h, w)."""
    arr = _to_numpy(img)
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(size * w / h)
        else:
            nh, nw = int(size * h / w), size
    else:
        nh, nw = size
    if _is_pil(img):
        modes = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
                 "bicubic": Image.BICUBIC}
        return img.resize((nw, nh), modes.get(interpolation, Image.BILINEAR))
    import jax
    method = {"nearest": "nearest", "bilinear": "linear",
              "bicubic": "cubic"}.get(interpolation, "linear")
    out_shape = (nh, nw) + arr.shape[2:]
    out = jax.image.resize(arr.astype("float32"), out_shape, method=method)
    out = np.asarray(out)
    return out.astype(arr.dtype) if arr.dtype == np.uint8 else out


def crop(img, top, left, height, width):
    if _is_pil(img):
        return img.crop((left, top, left + width, top + height))
    return _to_numpy(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr_h, arr_w = _to_numpy(img).shape[:2]
    th, tw = output_size
    top = int(round((arr_h - th) / 2.0))
    left = int(round((arr_w - tw) / 2.0))
    return crop(img, top, left, th, tw)


def hflip(img):
    if _is_pil(img):
        return img.transpose(Image.FLIP_LEFT_RIGHT)
    return _to_numpy(img)[:, ::-1]


def vflip(img):
    if _is_pil(img):
        return img.transpose(Image.FLIP_TOP_BOTTOM)
    return _to_numpy(img)[::-1]


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_numpy(img)
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4  # left, top, right, bottom
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    l, t, r, b = padding
    pads = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    out = np.pad(arr, pads, mode=mode, **kw)
    if _is_pil(img):
        return Image.fromarray(out)
    return out


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    if _is_pil(img):
        modes = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR}
        return img.rotate(angle, modes.get(interpolation, Image.NEAREST),
                          expand=expand, center=center, fillcolor=fill)
    arr = _to_numpy(img)
    k = int(round(angle / 90.0)) % 4
    if not np.isclose(angle % 90, 0):
        raise NotImplementedError(
            "ndarray rotate supports multiples of 90 deg; pass a PIL image "
            "for arbitrary angles")
    return np.rot90(arr, k).copy()


def to_grayscale(img, num_output_channels=1):
    arr = _to_numpy(img).astype("float32")
    if arr.ndim == 2:
        gray = arr
    else:
        gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    gray = np.repeat(gray[..., None], num_output_channels, axis=-1)
    if _is_pil(img):
        return Image.fromarray(gray.astype("uint8").squeeze())
    return gray.astype(_to_numpy(img).dtype)


def adjust_brightness(img, brightness_factor):
    arr = _to_numpy(img).astype("float32") * brightness_factor
    out = np.clip(arr, 0, 255)
    if _is_pil(img):
        return Image.fromarray(out.astype("uint8"))
    return out.astype(_to_numpy(img).dtype)


def adjust_contrast(img, contrast_factor):
    arr = _to_numpy(img).astype("float32")
    mean = arr.mean()
    out = np.clip((arr - mean) * contrast_factor + mean, 0, 255)
    if _is_pil(img):
        return Image.fromarray(out.astype("uint8"))
    return out.astype(_to_numpy(img).dtype)
