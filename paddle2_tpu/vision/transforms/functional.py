"""Functional image transforms (reference
python/paddle/vision/transforms/functional.py; independent numpy/PIL
implementation — TPU note: transforms are host-side data prep, so they stay
in numpy/PIL and never trace)."""

from __future__ import annotations

import numbers
from typing import Sequence

import numpy as np

try:
    from PIL import Image
    _HAS_PIL = True
except ImportError:  # pragma: no cover
    _HAS_PIL = False


def _is_pil(img):
    return _HAS_PIL and isinstance(img, Image.Image)


def _to_numpy(img) -> np.ndarray:
    """HWC uint8/float numpy view of a PIL image / ndarray / Tensor."""
    if _is_pil(img):
        return np.asarray(img)
    from ...framework.tensor import Tensor
    if isinstance(img, Tensor):
        return np.asarray(img.numpy())
    return np.asarray(img)


def to_tensor(pic, data_format="CHW"):
    """functional.py to_tensor: HWC [0,255] -> CHW float32 [0,1] Tensor."""
    from ... import to_tensor as paddle_to_tensor
    arr = _to_numpy(pic)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if arr.dtype == np.uint8:
        arr = arr.astype("float32") / 255.0
    else:
        arr = arr.astype("float32")
    if data_format == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    return paddle_to_tensor(arr)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = _to_numpy(img).astype("float32")
    mean = np.asarray(mean, dtype="float32")
    std = np.asarray(std, dtype="float32")
    if data_format == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    return (arr - mean.reshape(shape)) / std.reshape(shape)


def resize(img, size, interpolation="bilinear"):
    """size: int (short side) or (h, w)."""
    arr = _to_numpy(img)
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(size * w / h)
        else:
            nh, nw = int(size * h / w), size
    else:
        nh, nw = size
    if _is_pil(img):
        modes = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
                 "bicubic": Image.BICUBIC}
        return img.resize((nw, nh), modes.get(interpolation, Image.BILINEAR))
    import jax
    method = {"nearest": "nearest", "bilinear": "linear",
              "bicubic": "cubic"}.get(interpolation, "linear")
    out_shape = (nh, nw) + arr.shape[2:]
    out = jax.image.resize(arr.astype("float32"), out_shape, method=method)
    out = np.asarray(out)
    return out.astype(arr.dtype) if arr.dtype == np.uint8 else out


def crop(img, top, left, height, width):
    if _is_pil(img):
        return img.crop((left, top, left + width, top + height))
    return _to_numpy(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr_h, arr_w = _to_numpy(img).shape[:2]
    th, tw = output_size
    top = int(round((arr_h - th) / 2.0))
    left = int(round((arr_w - tw) / 2.0))
    return crop(img, top, left, th, tw)


def hflip(img):
    if _is_pil(img):
        return img.transpose(Image.FLIP_LEFT_RIGHT)
    return _to_numpy(img)[:, ::-1]


def vflip(img):
    if _is_pil(img):
        return img.transpose(Image.FLIP_TOP_BOTTOM)
    return _to_numpy(img)[::-1]


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _to_numpy(img)
    if isinstance(padding, numbers.Number):
        padding = (padding,) * 4  # left, top, right, bottom
    elif len(padding) == 2:
        padding = (padding[0], padding[1], padding[0], padding[1])
    l, t, r, b = padding
    pads = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    out = np.pad(arr, pads, mode=mode, **kw)
    if _is_pil(img):
        return Image.fromarray(out)
    return out


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    if _is_pil(img):
        modes = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR}
        return img.rotate(angle, modes.get(interpolation, Image.NEAREST),
                          expand=expand, center=center, fillcolor=fill)
    arr = _to_numpy(img)
    k = int(round(angle / 90.0)) % 4
    if not np.isclose(angle % 90, 0):
        raise NotImplementedError(
            "ndarray rotate supports multiples of 90 deg; pass a PIL image "
            "for arbitrary angles")
    return np.rot90(arr, k).copy()


def to_grayscale(img, num_output_channels=1):
    arr = _to_numpy(img).astype("float32")
    if arr.ndim == 2:
        gray = arr
    else:
        gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    gray = np.repeat(gray[..., None], num_output_channels, axis=-1)
    if _is_pil(img):
        return Image.fromarray(gray.astype("uint8").squeeze())
    return gray.astype(_to_numpy(img).dtype)


def adjust_brightness(img, brightness_factor):
    arr = _to_numpy(img).astype("float32") * brightness_factor
    out = np.clip(arr, 0, 255)
    if _is_pil(img):
        return Image.fromarray(out.astype("uint8"))
    return out.astype(_to_numpy(img).dtype)


def adjust_contrast(img, contrast_factor):
    arr = _to_numpy(img).astype("float32")
    mean = arr.mean()
    out = np.clip((arr - mean) * contrast_factor + mean, 0, 255)
    if _is_pil(img):
        return Image.fromarray(out.astype("uint8"))
    return out.astype(_to_numpy(img).dtype)


def adjust_saturation(img, saturation_factor):
    """functional.py adjust_saturation: blend with the grayscale image."""
    arr = _to_numpy(img).astype("float32")
    gray = (arr[..., 0] * 0.299 + arr[..., 1] * 0.587
            + arr[..., 2] * 0.114)[..., None]
    out = np.clip(gray + (arr - gray) * saturation_factor, 0, 255)
    if _is_pil(img):
        return Image.fromarray(out.astype("uint8"))
    return out.astype(_to_numpy(img).dtype)


def adjust_hue(img, hue_factor):
    """functional.py adjust_hue: shift hue by hue_factor (in [-0.5, 0.5]
    turns) through an RGB->HSV->RGB round trip."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = _to_numpy(img).astype("float32") / 255.0
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    maxc = arr[..., :3].max(-1)
    minc = arr[..., :3].min(-1)
    v = maxc
    d = maxc - minc
    s = np.where(maxc > 0, d / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(d, 1e-12)
    rc = (maxc - r) / dz
    gc = (maxc - g) / dz
    bc = (maxc - b) / dz
    h = np.where(r == maxc, bc - gc,
                 np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
    h = (h / 6.0) % 1.0
    h = np.where(d == 0, 0.0, h)
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype("int32") % 6
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    out = np.clip(np.stack([r2, g2, b2], -1) * 255.0, 0, 255)
    if _is_pil(img):
        return Image.fromarray(out.astype("uint8"))
    return out.astype(_to_numpy(img).dtype)


def erase(img, i, j, h, w, v, inplace=False):
    """functional.py erase: fill img[i:i+h, j:j+w] with v."""
    from ...framework.tensor import Tensor
    if isinstance(img, Tensor):
        import jax.numpy as jnp
        arr = img._data
        val = jnp.broadcast_to(jnp.asarray(v, arr.dtype),
                               arr[..., i:i + h, j:j + w].shape)
        out = arr.at[..., i:i + h, j:j + w].set(val)
        if inplace:
            img._replace_data(out)
            return img
        return Tensor(out)
    arr = _to_numpy(img).copy()
    arr[i:i + h, j:j + w] = v
    if _is_pil(img):
        return Image.fromarray(arr.astype("uint8"))
    return arr


def _warp_bilinear(arr, inv_matrix, fill=0):
    """Inverse-map warp with bilinear sampling. arr HWC; inv maps output
    (x, y, 1) -> input (x, y)."""
    H, W = arr.shape[:2]
    ys, xs = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
    ones = np.ones_like(xs)
    coords = np.stack([xs, ys, ones], 0).reshape(3, -1).astype("float64")
    src = inv_matrix @ coords
    if inv_matrix.shape[0] == 3:
        src = src[:2] / np.maximum(np.abs(src[2:3]), 1e-12) * np.sign(
            src[2:3])
    sx = src[0].reshape(H, W)
    sy = src[1].reshape(H, W)
    x0 = np.floor(sx).astype(int)
    y0 = np.floor(sy).astype(int)
    wx = sx - x0
    wy = sy - y0
    out = np.zeros_like(arr, dtype="float32")
    acc = np.zeros(arr.shape[:2], dtype="float32")
    for dy in (0, 1):
        for dx in (0, 1):
            xi = x0 + dx
            yi = y0 + dy
            wgt = (wx if dx else 1 - wx) * (wy if dy else 1 - wy)
            valid = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
            xi_c = np.clip(xi, 0, W - 1)
            yi_c = np.clip(yi, 0, H - 1)
            pix = arr[yi_c, xi_c].astype("float32")
            out += pix * (wgt * valid)[..., None]
            acc += wgt * valid
    out = out + np.asarray(fill, "float32") * (1 - acc)[..., None]
    return out


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """functional.py affine: rotation+translation+scale+shear about
    center, implemented as an inverse-matrix bilinear warp."""
    arr = _to_numpy(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[..., None]
    H, W = arr.shape[:2]
    if center is None:
        center = ((W - 1) * 0.5, (H - 1) * 0.5)
    rot = np.deg2rad(angle)
    sx, sy = [np.deg2rad(s) for s in (shear if isinstance(
        shear, (list, tuple)) else (shear, 0.0))]
    cx, cy = center
    tx, ty = translate
    # forward matrix M = T(center) R S Shear T(-center) + translate
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    M = np.array([[a, b, 0.0], [c, d, 0.0], [0, 0, 1.0]]) * 1.0
    M[:2, :2] *= scale
    M[0, 2] = cx + tx - M[0, 0] * cx - M[0, 1] * cy
    M[1, 2] = cy + ty - M[1, 0] * cx - M[1, 1] * cy
    inv = np.linalg.inv(M)
    out = _warp_bilinear(arr, inv, fill)
    out = np.clip(out, 0, 255) if arr.dtype == np.uint8 else out
    if squeeze:
        out = out[..., 0]
    if _is_pil(img):
        return Image.fromarray(out.astype("uint8"))
    return out.astype(arr.dtype)


def _homography(startpoints, endpoints):
    """Solve the 3x3 projective transform mapping endpoints->startpoints
    (the inverse map the warp needs)."""
    A = []
    bvec = []
    for (ex, ey), (sx, sy) in zip(endpoints, startpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        bvec.append(sx)
        A.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        bvec.append(sy)
    h = np.linalg.solve(np.asarray(A, "float64"),
                        np.asarray(bvec, "float64"))
    return np.concatenate([h, [1.0]]).reshape(3, 3)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """functional.py perspective: 4-point projective warp."""
    arr = _to_numpy(img)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[..., None]
    inv = _homography(startpoints, endpoints)
    out = _warp_bilinear(arr, inv, fill)
    out = np.clip(out, 0, 255) if arr.dtype == np.uint8 else out
    if squeeze:
        out = out[..., 0]
    if _is_pil(img):
        return Image.fromarray(out.astype("uint8"))
    return out.astype(arr.dtype)
