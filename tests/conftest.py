"""Test env: force CPU PJRT with 8 virtual devices BEFORE jax initializes.

Mirrors the reference's fake-device strategy (fake_cpu_device.h /
test/custom_runtime/): all tests — including multi-chip sharding tests — run
on a virtual 8-device CPU mesh so CI needs no accelerator.
"""

import os

# FORCE cpu: the driver env pins JAX_PLATFORMS to the tunneled TPU and a
# site hook re-prepends it, so the env var alone is not enough — every tiny
# test compile would pay a network roundtrip. config.update after import is
# the override that sticks (backend not yet initialized).
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    np.random.seed(0)
    import paddle2_tpu as paddle
    paddle.seed(0)
    yield
