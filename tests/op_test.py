"""OpTest harness: op-vs-NumPy forward check + numeric finite-difference grads.

Parity with the reference's test/legacy_test/op_test.py:418 (check_output at
:2139, check_grad vs get_numeric_gradient at :3129,:148), rebuilt for the
eager tape: run the paddle2_tpu op on Tensors, compare against a NumPy
reference, then perturb each input elementwise to finite-difference the
gradient and compare with tape backward.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

import paddle2_tpu as paddle


def _tolerances(dtype) -> Dict[str, float]:
    dt = np.dtype(str(np.dtype(dtype)))
    if dt == np.float16 or str(dtype) == "bfloat16":
        return dict(rtol=1e-2, atol=1e-2)
    if dt == np.float32:
        return dict(rtol=1e-5, atol=1e-6)
    return dict(rtol=1e-7, atol=1e-9)


def check_output(op: Callable, np_ref: Callable, inputs: Sequence[np.ndarray],
                 rtol: Optional[float] = None, atol: Optional[float] = None,
                 **kwargs) -> None:
    """Compare op(Tensors) against np_ref(ndarrays)."""
    tensors = [paddle.to_tensor(a) for a in inputs]
    out = op(*tensors, **kwargs)
    ref = np_ref(*inputs, **kwargs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    refs = ref if isinstance(ref, (tuple, list)) else [ref]
    for o, r in zip(outs, refs):
        tol = _tolerances(o.dtype)
        if rtol is not None:
            tol["rtol"] = rtol
        if atol is not None:
            tol["atol"] = atol
        np.testing.assert_allclose(np.asarray(o.numpy(), np.float64),
                                   np.asarray(r, np.float64), **tol)


def numeric_grad(op: Callable, inputs: List[np.ndarray], idx: int,
                 delta: float = 5e-3, **kwargs) -> np.ndarray:
    """Central finite difference of sum(op) w.r.t. inputs[idx]
    (get_numeric_gradient parity).

    Vectorized: all 2*N perturbed evaluations run as ONE vmapped+jitted XLA
    program (the op's eager path accepts tracer payloads, same mechanism as
    jit.to_static). Ops that cannot trace (data-dependent shapes) fall back
    to the per-element Python loop.
    """
    import jax
    import jax.numpy as jnp
    from paddle2_tpu.framework import core
    from paddle2_tpu.framework.tensor import Tensor

    shape = inputs[idx].shape
    arrs = [jnp.asarray(a) for a in inputs]
    target_dtype = arrs[idx].dtype

    def f(x_flat):
        xs = [x_flat.reshape(shape).astype(target_dtype) if j == idx else a
              for j, a in enumerate(arrs)]
        with core.no_grad():
            ts = [Tensor(a) for a in xs]
            out = op(*ts, **kwargs)
        outs = out if isinstance(out, (tuple, list)) else [out]
        tot = jnp.float32(0.0)
        for o in outs:
            if jnp.issubdtype(o._data.dtype, jnp.inexact):
                tot = tot + jnp.sum(o._data.astype(jnp.float32))
        return tot

    base = jnp.asarray(inputs[idx], jnp.float32).reshape(-1)
    n = base.size
    try:
        eye = jnp.eye(n, dtype=base.dtype) * jnp.float32(delta)
        fd = jax.jit(jax.vmap(
            lambda e: (f(base + e) - f(base - e)) / (2.0 * delta)))
        return np.asarray(fd(eye), np.float64).reshape(shape)
    except Exception:
        pass  # untraceable op: per-element loop below

    g = np.zeros(n, dtype=np.float64)
    work = [a.copy() for a in inputs]
    flat = work[idx].reshape(-1)

    def f_eager(xs):
        ts = [paddle.to_tensor(a) for a in xs]
        out = op(*ts, **kwargs)
        outs = out if isinstance(out, (tuple, list)) else [out]
        return float(sum(o.sum().item() for o in outs
                         if np.issubdtype(np.dtype(str(o.dtype)), np.floating)))

    for i in range(n):
        orig = flat[i]
        flat[i] = orig + delta
        fp = f_eager(work)
        flat[i] = orig - delta
        fm = f_eager(work)
        flat[i] = orig
        g[i] = (fp - fm) / (2 * delta)
    return g.reshape(shape)


def check_grad(op: Callable, inputs: Sequence[np.ndarray],
               grad_inputs: Optional[Sequence[int]] = None,
               delta: float = 5e-3, rtol: float = 5e-3, atol: float = 1e-4,
               **kwargs) -> None:
    """Tape backward vs numeric gradient for each (float) input."""
    inputs = [np.asarray(a, np.float64).astype(np.float32) for a in inputs]
    tensors = [paddle.to_tensor(a, stop_gradient=False) for a in inputs]
    out = op(*tensors, **kwargs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    loss = None
    for o in outs:
        if np.issubdtype(np.dtype(str(o.dtype)), np.floating):
            term = o.sum()
            loss = term if loss is None else loss + term
    assert loss is not None, "op has no float output to differentiate"
    loss.backward()

    indices = grad_inputs if grad_inputs is not None else range(len(inputs))
    for i in indices:
        assert tensors[i].grad is not None, f"input {i} got no gradient"
        analytic = tensors[i].grad.numpy().astype(np.float64)
        numeric = numeric_grad(op, [a.copy() for a in inputs], i,
                               delta=delta, **kwargs)
        np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol,
                                   err_msg=f"grad mismatch for input {i}")
