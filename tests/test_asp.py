"""2:4 structured sparsity (incubate/asp.py; reference incubate/asp/)."""

import numpy as np
import pytest

import paddle2_tpu as paddle
import paddle2_tpu.optimizer as opt
from paddle2_tpu import nn
from paddle2_tpu.incubate import asp


def test_create_mask_keeps_top2_of_4():
    w = paddle.to_tensor(np.array(
        [[1.0, -3.0, 0.5, 2.0, 4.0, 0.1, -0.2, 5.0]], np.float32))
    mask = asp.create_mask(w)
    np.testing.assert_array_equal(
        np.asarray(mask), [[0, 1, 0, 1, 1, 0, 0, 1]])


def test_prune_model_and_density():
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    masks = asp.prune_model(m)
    assert len(masks) == 2
    for lin in (m[0], m[2]):
        assert asp.check_sparsity(lin.weight)
        assert asp.calculate_density(lin.weight) <= 0.5 + 1e-6


def test_excluded_layers_skipped():
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8))
    asp.set_excluded_layers(["0.weight"])
    try:
        masks = asp.prune_model(m)
        assert "0.weight" not in masks and "1.weight" in masks
        assert not asp.check_sparsity(m[0].weight)  # left dense
    finally:
        asp.reset_excluded_layers()


def test_decorated_optimizer_preserves_pattern():
    paddle.seed(0)
    m = nn.Linear(16, 16)
    asp.prune_model(m)
    o = asp.decorate(opt.AdamW(learning_rate=0.05,
                               parameters=m.parameters()))
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(8, 16).astype(np.float32))
    y = paddle.to_tensor(rs.randn(8, 16).astype(np.float32))
    for _ in range(5):
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
    assert asp.check_sparsity(m.weight)          # 2:4 survives training
    assert asp.calculate_density(m.weight) <= 0.5 + 1e-6
